"""Context / sequence parallelism — ring attention.

Capability BEYOND the reference (SURVEY.md §5.7: the reference's
``dot_product_attention`` materializes O(T²) scores, practical max a few
thousand tokens).  Here sequences shard over the mesh ``seq`` axis;
each device holds a [B, T/n, ...] slice, K/V blocks rotate around the
ring via ``ppermute`` (ICI neighbor links — ring topology matches TPU
torus), and softmax is accumulated online (running max + normalizer), so
per-device memory is O(T/n · T/n) per step and the full [T,T] matrix
never exists.

Ring vs Ulysses decision (SURVEY.md §5.7): ring's neighbor-only traffic
fits ICI better than all-to-all head-resharding at pod scale — this is
the default CP strategy.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from deeplearning4j_tpu.utils.jax_compat import pcast, shard_map

NEG_INF = -1e30


def _block_attention(q, k, v, scale, mask):
    """Scores for one (q-block, kv-block) pair.
    q [B,H,Tq,D], k/v [B,H,Tk,D], mask broadcastable [Tq,Tk] or None.
    Returns (unnormalized out, row max, row sumexp)."""
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if mask is not None:
        scores = jnp.where(mask, scores, NEG_INF)
    m = jnp.max(scores, axis=-1)                      # [B,H,Tq]
    p = jnp.exp(scores - m[..., None])
    if mask is not None:
        # rows with no visible keys: exp(NEG_INF - NEG_INF) = 1 → zero them
        any_visible = jnp.any(mask, axis=-1)          # [Tq,Tk] → [Tq]
        p = p * jnp.broadcast_to(any_visible[None, None, :, None], p.shape)
        m = jnp.where(any_visible[None, None, :], m, NEG_INF)
    l = jnp.sum(p, axis=-1)                           # [B,H,Tq]
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return o, m, l


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   mesh: Mesh, axis: str = "seq", n_heads: int = 1,
                   causal: bool = False, data_axis: str | None = None,
                   head_axis: str | None = None, use_flash: bool = False,
                   flash_block: int = 128) -> jnp.ndarray:
    """Multi-head ring attention.  q/k/v: [B, T, H*D] GLOBALLY, sharded
    over ``axis`` on dim 1.  Returns [B, T, H*D] with the same sharding.

    Inside shard_map each device sees its local [B, T/n, H*D] slice; K/V
    rotate n steps around the ring; online-softmax accumulators merge
    per-block partial results exactly.

    Composable mesh axes: ``data_axis`` shards the batch dim (dp×sp);
    ``head_axis`` shards the HEADS across a tensor-parallel axis (tp×sp —
    the ring rotates within each head group, Ulysses-meets-ring layout;
    ``n_heads`` is the GLOBAL head count and must divide by the axis size).
    """
    n_dev = mesh.shape[axis]
    if head_axis and n_heads % mesh.shape[head_axis]:
        raise ValueError(f"n_heads={n_heads} not divisible by mesh axis "
                         f"'{head_axis}' size {mesh.shape[head_axis]}")
    local_heads = n_heads // mesh.shape[head_axis] if head_axis else n_heads

    def local(q, k, v):
        b, t_local, dmodel = q.shape
        n_heads = local_heads
        dh = dmodel // n_heads
        scale = 1.0 / math.sqrt(dh)
        qh = q.reshape(b, t_local, n_heads, dh).transpose(0, 2, 1, 3)
        kh = k.reshape(b, t_local, n_heads, dh).transpose(0, 2, 1, 3)
        vh = v.reshape(b, t_local, n_heads, dh).transpose(0, 2, 1, 3)
        my_idx = lax.axis_index(axis)

        def step(carry, s):
            k_blk, v_blk, o, m, l = carry
            src_idx = (my_idx - s) % n_dev  # which device this kv block came from
            if use_flash:
                # Pallas blockwise kernel: VMEM score tiles, no per-block
                # [Tq,Tk] matrix in HBM (SURVEY §5.7/§7.7)
                from deeplearning4j_tpu.ops.pallas import flash_attention_block
                o_b, m_b, l_b = flash_attention_block(
                    qh, k_blk, v_blk, scale=scale, causal=causal,
                    q_offset=my_idx * t_local, k_offset=src_idx * t_local,
                    block_q=flash_block, block_k=flash_block)
                # kernel accumulates in f32; match the scan carry dtypes
                # (bf16 inputs carry bf16 accumulators like the jnp path)
                o_b = o_b.astype(o.dtype)
                m_b = m_b.astype(m.dtype)
                l_b = l_b.astype(l.dtype)
            else:
                if causal:
                    q_pos = my_idx * t_local + jnp.arange(t_local)
                    k_pos = src_idx * t_local + jnp.arange(t_local)
                    mask = q_pos[:, None] >= k_pos[None, :]
                else:
                    mask = None
                o_b, m_b, l_b = _block_attention(qh, k_blk, v_blk, scale, mask)
            # merge online-softmax accumulators
            m_new = jnp.maximum(m, m_b)
            c_old = jnp.exp(m - m_new)
            c_blk = jnp.exp(m_b - m_new)
            o = o * c_old[..., None] + o_b * c_blk[..., None]
            l = l * c_old + l_b * c_blk
            # rotate kv to the next device (neighbor ring over ICI)
            perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]
            k_blk = lax.ppermute(k_blk, axis, perm)
            v_blk = lax.ppermute(v_blk, axis, perm)
            return (k_blk, v_blk, o, m_new, l), None

        # initial accumulators must be marked device-varying for the scan
        # carry to type-check under shard_map's VMA tracking — over EVERY
        # sharded axis in play (seq ring + optional data/head axes)
        varying = tuple(a for a in (axis, data_axis, head_axis) if a)
        o0 = jnp.zeros_like(qh)
        m0 = pcast(jnp.full(qh.shape[:-1], NEG_INF, qh.dtype), varying, to="varying")
        l0 = pcast(jnp.zeros(qh.shape[:-1], qh.dtype), varying, to="varying")
        (k_f, v_f, o, m, l), _ = lax.scan(step, (kh, vh, o0, m0, l0),
                                          jnp.arange(n_dev))
        out = o / jnp.maximum(l[..., None], 1e-20)
        return out.transpose(0, 2, 1, 3).reshape(b, t_local, dmodel)

    spec = P(data_axis, axis, head_axis)
    # check_vma off on the flash path: the Pallas interpreter (CPU tests)
    # can't yet thread varying-manual-axes through its internal jaxpr eval
    return shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=not use_flash)(q, k, v)


def reference_attention(q, k, v, n_heads: int, causal: bool = False):
    """Single-device ground truth for ring_attention tests."""
    from deeplearning4j_tpu.ops.attention import multi_head_attention
    return multi_head_attention(q, k, v, n_heads=n_heads, causal=causal)


def ulysses_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      mesh: Mesh, axis: str = "seq", n_heads: int = 1,
                      causal: bool = False,
                      data_axis: str | None = None) -> jnp.ndarray:
    """DeepSpeed-Ulysses-style sequence parallelism: two ``all_to_all``s
    instead of a ring.  q/k/v: [B, T, H*D] globally, sharded over
    ``axis`` on the token dim.  The first all_to_all re-shards from
    token-sharded to HEAD-sharded (each device receives every token for
    H/n of the heads), attention runs dense per local head group, and the
    inverse all_to_all restores token sharding.

    Complement to :func:`ring_attention` (SURVEY §5.7): Ulysses moves
    activations twice through all-to-all (bandwidth ∝ T·H·D/n per
    device) but runs each head's attention un-tiled, so it wins when
    n ≪ heads and sequence blocks are small; the ring wins at pod scale
    where neighbor-only ICI traffic matters.  Requires n_heads % n == 0.
    """
    n_dev = mesh.shape[axis]
    if n_heads % n_dev:
        raise ValueError(f"n_heads={n_heads} must be divisible by the "
                         f"'{axis}' axis size {n_dev} for Ulysses SP")

    def local(q, k, v):
        b, t_local, dmodel = q.shape
        dh = dmodel // n_heads

        def scatter_heads(x):
            xh = x.reshape(b, t_local, n_heads, dh)
            # tokens gathered, heads scattered: [B, T, H/n, dh]
            return lax.all_to_all(xh, axis, split_axis=2, concat_axis=1,
                                  tiled=True)

        qh, kh, vh = scatter_heads(q), scatter_heads(k), scatter_heads(v)
        qh = qh.transpose(0, 2, 1, 3)     # [B, H/n, T, dh]
        kh = kh.transpose(0, 2, 1, 3)
        vh = vh.transpose(0, 2, 1, 3)
        scale = 1.0 / math.sqrt(dh)
        scores = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * scale
        if causal:
            t = scores.shape[-1]
            mask = jnp.tril(jnp.ones((t, t), bool))
            scores = jnp.where(mask[None, None], scores, NEG_INF)
        out = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(scores, -1), vh)
        out = out.transpose(0, 2, 1, 3)   # [B, T, H/n, dh]
        # inverse: tokens scattered back, heads gathered
        out = lax.all_to_all(out, axis, split_axis=1, concat_axis=2,
                             tiled=True)  # [B, T/n, H, dh]
        return out.reshape(b, t_local, dmodel)

    spec = P(data_axis, axis)
    return shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec)(q, k, v)
