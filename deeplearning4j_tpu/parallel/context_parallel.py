"""Deprecated shim — context/sequence parallelism moved to the unified
path.

.. deprecated::
    Ring and Ulysses attention live in
    :mod:`deeplearning4j_tpu.parallel.unified` (the canonical home for
    every composable collective over the unified mesh — axis names come
    from ``parallel.mesh.MESH_AXES``, ``AXIS_SEQ`` here).  This module
    stays so existing imports keep working; new code imports from
    ``parallel.unified`` (or the ``deeplearning4j_tpu.parallel``
    package, which re-exports it).
"""

from __future__ import annotations

import warnings

from deeplearning4j_tpu.parallel.unified import (  # noqa: F401
    NEG_INF, _block_attention, reference_attention, ring_attention,
    ulysses_attention)

warnings.warn(
    "deeplearning4j_tpu.parallel.context_parallel is deprecated; import "
    "ring_attention/ulysses_attention from deeplearning4j_tpu.parallel "
    "(unified-mesh path, docs/PARALLELISM.md)",
    DeprecationWarning, stacklevel=2)
