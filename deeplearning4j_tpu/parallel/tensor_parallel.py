"""Tensor parallelism — NamedSharding rules over the ``model`` axis.

Capability BEYOND the reference (it has no TP; SURVEY.md §2.7).  Design
per the Megatron/GSPMD recipe: attention QKV projections and FFN
in-projection shard column-wise (output features over ``model``),
attention output and FFN out-projection shard row-wise (input features
over ``model``); XLA inserts the (all-gather / reduce-scatter) pair —
no manual collectives.

The rules are keyed by parameter-path regexes so they apply to the BERT
module's named pytree and to any ComputationGraph with matching names.
"""

from __future__ import annotations

import re
from typing import Any, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# path-regex → PartitionSpec for 2-D kernels ([in, out]); 1-D arrays
# (bias, layernorm) follow their producing kernel's OUT sharding when that
# dim is sharded column-wise, else replicate.
BERT_TP_RULES: list[tuple[str, P]] = [
    (r"attention/(query|key|value)/kernel$", P(None, "model")),   # column
    (r"attention/output/kernel$", P("model", None)),              # row
    (r"intermediate/kernel$", P(None, "model")),                  # column
    (r"(?<!attention/)output/kernel$", P("model", None)),         # FFN out, row
    (r"attention/(query|key|value)/bias$", P("model")),
    (r"intermediate/bias$", P("model")),
    (r"embeddings/word_embeddings$", P(None, None)),              # replicated (tied head)
]


def rule_axes(rules: Optional[list[tuple[str, P]]] = None) -> set[str]:
    """Every mesh-axis name a TP rule set mentions (the analyzer resolves
    these against ``mesh.MESH_AXES`` and against the DP batch axes)."""
    rules = rules if rules is not None else BERT_TP_RULES
    axes: set[str] = set()
    for _, spec in rules:
        for entry in spec:
            if entry is None:
                continue
            if isinstance(entry, (tuple, list)):
                axes.update(str(a) for a in entry)
            else:
                axes.add(str(entry))
    return axes


def _path_str(path) -> str:
    parts = []
    for entry in path:
        if hasattr(entry, "key"):
            parts.append(str(entry.key))
        elif hasattr(entry, "idx"):
            parts.append(str(entry.idx))
        else:
            parts.append(str(entry))
    return "/".join(parts)


def tp_sharding_tree(params: Any, mesh: Mesh,
                     rules: Optional[list[tuple[str, P]]] = None) -> Any:
    """Pytree of NamedShardings matching ``params``; unmatched leaves are
    replicated."""
    rules = rules if rules is not None else BERT_TP_RULES
    compiled = [(re.compile(pattern), spec) for pattern, spec in rules]

    def spec_for(path, leaf):
        s = _path_str(path)
        for pattern, spec in compiled:
            if pattern.search(s):
                return NamedSharding(mesh, spec)
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(spec_for, params)


def shard_params(params: Any, mesh: Mesh,
                 rules: Optional[list[tuple[str, P]]] = None) -> Any:
    """Place ``params`` according to the TP rules (device_put with layout —
    the one-time resharding cost of entering TP execution)."""
    shardings = tp_sharding_tree(params, mesh, rules)
    return jax.tree_util.tree_map(jax.device_put, params, shardings)


def tp_jit(fn, params_shardings, **jit_kwargs):
    """jit with parameter in_shardings bound (GSPMD partitions the rest)."""
    return jax.jit(fn, in_shardings=(params_shardings,), **jit_kwargs)
