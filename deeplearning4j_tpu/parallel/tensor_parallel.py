"""Deprecated shim — tensor parallelism is a layout on the unified mesh.

.. deprecated::
    The per-layer-family TP rule tables and the sharding-tree builders
    live in :mod:`deeplearning4j_tpu.parallel.mesh` (the single source
    of truth every layout resolves against); ``tp_jit`` lives in
    :mod:`deeplearning4j_tpu.parallel.unified`.  Training with TP no
    longer needs this module at all: ``Trainer(layout="tp2")`` (or
    ``"dp2xtp2"``) places parameters by the same rules.  This module
    stays so existing imports keep working.
"""

from __future__ import annotations

import warnings

from deeplearning4j_tpu.parallel.mesh import (  # noqa: F401
    BERT_TP_RULES, rule_axes, shard_params, tp_sharding_tree)
from deeplearning4j_tpu.parallel.unified import tp_jit  # noqa: F401

warnings.warn(
    "deeplearning4j_tpu.parallel.tensor_parallel is deprecated; TP "
    "rule tables live in parallel.mesh (TP_RULE_FAMILIES) and training "
    "uses Trainer(layout='tp2'/'dp2xtp2') — docs/PARALLELISM.md",
    DeprecationWarning, stacklevel=2)
