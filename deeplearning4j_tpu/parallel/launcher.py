"""Multi-host SPMD bootstrap — the Spark-orchestration replacement.

Parity with the reference's cluster story (SURVEY.md §2.7/§3.4: Spark
driver broadcasts the model, launches one long-lived worker per executor,
Aeron mesh forms via driver handshake): on TPU pods the runtime IS the
cluster — one process per host, ``jax.distributed.initialize`` handshakes
with the coordinator, and every jit'd step runs gang-scheduled SPMD.

Also provides the multi-process CPU test rig (DummyTransport parity,
SURVEY.md §4.2): spawn N local processes over loopback with
``spawn_local_cluster`` and run a function under a real multi-process
``jax.distributed`` runtime without any TPU pod.
"""

from __future__ import annotations

import os
import pickle
import signal
import subprocess
import sys
import tempfile
import time
from typing import Callable, Optional


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """``jax.distributed.initialize`` with env-var fallbacks
    (DL4J VoidConfiguration's controller address/ports equivalent).
    No-ops on single-process runs."""
    import jax
    from deeplearning4j_tpu.obs import tracing
    coordinator_address = coordinator_address or os.environ.get("DL4J_TPU_COORDINATOR")
    if num_processes is None:
        num_processes = int(os.environ.get("DL4J_TPU_NUM_PROCESSES", "1"))
    if process_id is None:
        process_id = int(os.environ.get("DL4J_TPU_PROCESS_ID", "0"))
    if num_processes <= 1:
        return
    with tracing.span("distributed_init", processes=num_processes,
                      process_id=process_id):
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)


_WORKER_TEMPLATE = r"""
import os, pickle, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count={local_devices}")
import jax
jax.config.update("jax_platforms", "cpu")  # sitecustomize may pin a TPU platform
from deeplearning4j_tpu.obs import flight_recorder as _fr
from deeplearning4j_tpu.obs import remote as _remote
_fr.install_from_env()   # black box: crash handlers + gang-deadline watchdog
_remote.install_from_env()   # telemetry federation: heartbeats + step stamps
jax.distributed.initialize(coordinator_address="127.0.0.1:{port}",
                           num_processes={n}, process_id={pid})
with open({fn_path!r}, "rb") as f:
    fn = pickle.load(f)
try:
    result = fn(jax.process_index(), jax.process_count())
    with open({out_path!r}, "wb") as f:
        pickle.dump(result, f)
finally:
    # ALSO on the failure path: an in-flight background cost analysis
    # (a real XLA compile on a worker thread) racing interpreter +
    # distributed shutdown aborts the process with a C++ terminate —
    # which would replace the Python traceback the launcher's stderr
    # tail surfaces; and a failing worker's buffered telemetry (the
    # steps leading up to the failure) is the telemetry worth flushing
    from deeplearning4j_tpu.obs import costmodel as _cm
    _cm.drain(timeout_s=60.0)
    _remote.close_router()
"""


class ClusterTimeoutError(RuntimeError):
    """The gang never completed within the wall budget.  Deliberately
    NOT retryable: its message embeds every child's stderr tail, which
    routinely contains coordinator-join noise ('connection refused')
    that must not be mistaken for a startup flake — re-running a
    timed-out gang would multiply an already-spent timeout.

    ``flight_dumps`` maps process id → that child's parsed flight-
    recorder dump lines (empty when the child never dumped)."""

    def __init__(self, *args):
        super().__init__(*args)
        self.flight_dumps: dict = {}


class ClusterStallError(RuntimeError):
    """One or more gang members' flight-recorder watchdogs fired (no
    step/exchange progress within the gang deadline): the per-host
    black boxes are attached as ``flight_dumps`` (pid → parsed JSONL
    lines with thread stacks, recent spans/events, metric snapshot).
    NOT retryable — a deterministic stall would just stall again."""

    def __init__(self, *args):
        super().__init__(*args)
        self.flight_dumps: dict = {}


# stderr fingerprints of a flaky STARTUP (stale coordinator port, racing
# binds) — worth retrying on a fresh port; genuine hangs/crashes are not.
# Deliberately NOT "connection refused": when one child dies for a real
# reason, its SIBLINGS routinely print coordinator-join 'connection
# refused' noise, and retrying a deterministic failure just multiplies it.
_STARTUP_FLAKE_MARKERS = ("address already in use", "failed to bind",
                          "errno 98")


def _is_startup_flake(e: BaseException) -> bool:
    from deeplearning4j_tpu.resilience.retry import default_retryable
    if isinstance(e, (ClusterTimeoutError, ClusterStallError)):
        return False
    if default_retryable(e):
        return True
    msg = str(e).lower()
    return isinstance(e, RuntimeError) and any(
        marker in msg for marker in _STARTUP_FLAKE_MARKERS)


def _terminate_then_kill(procs, grace: float = 3.0, first_pid: int = 0,
                         tail_fn=None) -> list[str]:
    """Stop every child (TERM, grace period, then KILL) and return each
    one's captured stderr tail — a timed-out gang must leave no orphans
    and no silent diagnostics.  ``tail_fn(pid) -> str`` supplies the
    tail when the children's output goes to files (GangHandle) instead
    of pipes."""
    for proc in procs:
        if proc.poll() is None:
            proc.terminate()
    deadline = time.monotonic() + grace
    for proc in procs:
        if proc.poll() is None:
            try:
                proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                proc.kill()
    tails = []
    for pid, proc in enumerate(procs):
        if tail_fn is not None:
            try:
                proc.wait(timeout=5.0)
            except (subprocess.TimeoutExpired, ValueError, OSError):
                pass
            text = tail_fn(first_pid + pid)
        else:
            try:
                _, stderr = proc.communicate(timeout=5.0)
            except (subprocess.TimeoutExpired, ValueError, OSError):
                stderr = b""
            text = (stderr or b"").decode(errors="replace")
        rc = proc.poll()
        tails.append(f"process {first_pid + pid} rc={rc} stderr tail: "
                     f"{text[-800:]}")
    return tails


def _collect_flight_dumps(workdir: str, n_processes: int) -> dict:
    """pid → parsed flight-recorder dump lines for every child that
    wrote one (missing/empty dumps → absent)."""
    from deeplearning4j_tpu.obs import flight_recorder
    dumps = {}
    for pid in range(n_processes):
        lines = flight_recorder.read_dump(
            os.path.join(workdir, f"flight_{pid}.jsonl"))
        if lines:
            dumps[pid] = lines
    return dumps


def _dump_summary(dumps: dict) -> str:
    """One readable line per dumped child for the raised error message
    (the full parsed dumps ride on the exception's ``flight_dumps``)."""
    if not dumps:
        return "no flight-recorder dumps found"
    lines = []
    for pid, entries in sorted(dumps.items()):
        header = next((e for e in entries if e.get("type") == "header"), {})
        live = next((e for e in entries if e.get("type") == "liveness"), {})
        threads = sum(1 for e in entries if e.get("type") == "thread")
        events = sum(1 for e in entries if e.get("type") == "event")
        lines.append(
            f"process {pid} black box: reason={header.get('reason')} "
            f"last_site={live.get('last_site')} "
            f"stalled_for_s={live.get('stalled_for_s')} "
            f"({threads} thread stacks, {events} ring events)")
    return "\n".join(lines)


class GangHandle:
    """A RUNNING local gang — the restartable handle the
    :class:`~deeplearning4j_tpu.resilience.supervisor.ClusterSupervisor`
    drives.  Construction spawns the child processes and returns
    immediately; callers either block in :meth:`wait` (the
    ``spawn_local_cluster`` path — identical semantics to the historical
    one-shot spawn) or poll :meth:`poll_exits` from a supervision loop,
    then :meth:`shutdown` the survivors and :meth:`collect_flight_dumps`
    when a member dies.

    ``child_env`` is the per-child env hook (``pid -> dict``), applied
    LAST so a supervisor can stamp per-worker identity (worker id,
    gang generation, resume pointer) over both the launcher defaults
    and the shared ``extra_env``."""

    def __init__(self, fn: Callable, n_processes: int, port: int,
                 local_devices: int = 1, timeout: float = 120.0,
                 extra_env: Optional[dict] = None,
                 gang_deadline: Optional[float] = None,
                 gang_fires: int = 1,
                 remote_ui: Optional[str] = None,
                 child_env: Optional[Callable[[int], dict]] = None):
        from deeplearning4j_tpu.obs import flight_recorder, tracing
        from deeplearning4j_tpu.obs import remote as obs_remote
        from deeplearning4j_tpu.resilience import faults
        faults.fire("launcher.spawn")
        self.n_processes = n_processes
        self.timeout = timeout
        self.gang_deadline = gang_deadline
        self.workdir = tempfile.mkdtemp(prefix="dl4j_tpu_cluster_")
        fn_path = os.path.join(self.workdir, "fn.pkl")
        with open(fn_path, "wb") as f:
            pickle.dump(fn, f)
        self.procs: list = []
        self.out_paths: list[str] = []
        trace_env = tracing.propagation_env()
        for pid in range(n_processes):
            out_path = os.path.join(self.workdir, f"out_{pid}.pkl")
            self.out_paths.append(out_path)
            script = _WORKER_TEMPLATE.format(
                n=n_processes, pid=pid, port=port, fn_path=fn_path,
                out_path=out_path, local_devices=local_devices)
            env = dict(os.environ)
            env.pop("XLA_FLAGS", None)  # template sets its own
            env.update(trace_env)
            # every child gets a black box: crash/SIGTERM dumps always,
            # plus a stall watchdog when a gang deadline is set.
            # Tracing is turned on alongside so the dump's ring carries
            # the last N spans, not just raw events.
            env[flight_recorder.DUMP_ENV] = os.path.join(
                self.workdir, f"flight_{pid}.jsonl")
            if gang_deadline is not None:
                env[flight_recorder.WATCHDOG_ENV] = str(float(gang_deadline))
                env[flight_recorder.WATCHDOG_FIRES_ENV] = str(int(gang_fires))
                env.setdefault("DL4J_TPU_TRACING", "1")
            if remote_ui:
                # telemetry federation: every child routes stats/
                # heartbeats to the coordinator UIServer under its own
                # worker label
                env[obs_remote.ENDPOINT_ENV] = remote_ui
                env[obs_remote.WORKER_ENV] = f"w{pid}"
            if extra_env:
                env.update(extra_env)
            if child_env is not None:
                env.update({k: str(v) for k, v in child_env(pid).items()})
            # children write to FILES, not pipes: the supervision loop
            # only polls exit codes, so a pipe nobody drains would wedge
            # a chatty child on a full 64KB buffer — and even the
            # blocking wait() drains sequentially (child N+1 could fill
            # its pipe while child N is being waited on)
            with open(os.path.join(self.workdir, f"stderr_{pid}.log"),
                      "wb") as err_f:
                self.procs.append(subprocess.Popen(
                    [sys.executable, "-c", script], env=env,
                    stdout=err_f, stderr=err_f))
        # ONE wall-clock budget for the whole gang: jax.distributed
        # blocks until every process joins, so child 0 timing out means
        # they all did
        self.started_at = time.monotonic()
        self.deadline = self.started_at + timeout

    # ------------------------------------------------- supervision surface
    def poll_exits(self) -> dict:
        """pid → return code for every child (None = still running).
        Non-blocking; the supervisor's detection loop."""
        return {pid: proc.poll() for pid, proc in enumerate(self.procs)}

    def running(self) -> bool:
        return any(proc.poll() is None for proc in self.procs)

    def stderr_tail(self, pid: int, limit: int = 800) -> str:
        """Last ``limit`` chars of the child's combined stdout/stderr
        file (children write to files so nothing ever blocks on an
        undrained pipe)."""
        try:
            with open(os.path.join(self.workdir, f"stderr_{pid}.log"),
                      "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - 4 * limit))
                return f.read().decode(errors="replace")[-limit:]
        except OSError:
            return ""

    def request_dumps(self, grace: float = 3.0) -> None:
        """Ask every still-alive child for its black box (SIGUSR1 → the
        flight recorder dumps and SURVIVES), then wait up to ``grace``
        for the dump files to GROW past their pre-signal size and go
        quiet — a dump written earlier in the generation (a watchdog
        grace fire, a health-monitor action) must not satisfy the wait
        and let teardown kill a child mid-append.  Separate from
        :meth:`shutdown` because jax's TSL preemption notifier owns
        SIGTERM in gang children — a SIGTERM never reaches the Python
        dump handler, so evidence must be collected before the stop
        signal.  Limitation: CPython runs signal handlers between
        bytecodes on the main thread, so a sibling wedged inside a
        native collective cannot answer — that state is the stall
        watchdog's job (it dumps from its own thread and exits 87)."""
        def sizes():
            out = {}
            for pid, p in enumerate(self.procs):
                try:
                    out[pid] = os.path.getsize(
                        os.path.join(self.workdir, f"flight_{pid}.jsonl"))
                except OSError:
                    out[pid] = -1
            return out

        before = sizes()
        alive = []
        for pid, p in enumerate(self.procs):
            if p.poll() is None:
                alive.append(pid)
                try:
                    p.send_signal(signal.SIGUSR1)
                except (ProcessLookupError, OSError):
                    pass
        if not alive:
            return
        deadline = time.monotonic() + grace
        prev = before
        while time.monotonic() < deadline:
            time.sleep(0.1)
            now = sizes()
            grown = all(now[pid] > before[pid] for pid in alive
                        if self.procs[pid].poll() is None)
            settled = all(now[pid] == prev[pid] for pid in alive)
            if grown and settled:
                return          # every reachable child dumped, writes quiet
            prev = now

    def shutdown(self, grace: float = 3.0) -> list[str]:
        """Terminate-then-kill every remaining child; returns each
        child's stderr tail (already-exited children just report)."""
        return _terminate_then_kill(self.procs, grace=grace,
                                    tail_fn=self.stderr_tail)

    def abort_timeout(self, reason: str,
                      extra_lines: Optional[list] = None
                      ) -> "ClusterTimeoutError":
        """Stop the whole gang and build the ``ClusterTimeoutError`` for
        a blown wall budget — one construction shared by the blocking
        :meth:`wait` and the supervisor's watch loop, so the message
        shape and the ``flight_dumps`` attachment can't drift."""
        tails = self.shutdown()
        dumps = self.collect_flight_dumps()
        err = ClusterTimeoutError(
            reason + "\n" + "\n".join((extra_lines or []) + tails)
            + "\n" + _dump_summary(dumps))
        err.flight_dumps = dumps
        return err

    def collect_flight_dumps(self) -> dict:
        return _collect_flight_dumps(self.workdir, self.n_processes)

    def results(self) -> list:
        """Return values of the children that completed (out pickles
        present).  Call after a clean gang exit."""
        results = []
        for path in self.out_paths:
            if os.path.exists(path):
                with open(path, "rb") as f:
                    results.append(pickle.load(f))
        return results

    # ----------------------------------------------- blocking collection
    def wait(self) -> list:
        """Block until the gang finishes; return every child's result or
        raise (``ClusterTimeoutError`` / ``ClusterStallError`` /
        ``RuntimeError``) with flight dumps attached — the historical
        ``spawn_local_cluster`` semantics."""
        from deeplearning4j_tpu.obs import flight_recorder
        procs, workdir = self.procs, self.workdir
        n_processes, timeout = self.n_processes, self.timeout
        gang_deadline = self.gang_deadline
        results = []
        errors = []
        stalled = []
        for pid, proc in enumerate(procs):
            try:
                proc.wait(timeout=max(0.1, self.deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                # a hung gang member past even the watchdog: stop EVERY
                # child (terminate → grace → kill) and surface each
                # one's stderr AND whatever black boxes landed — the
                # raised error must say which process wedged and why,
                # not just "timed out"
                raise self.abort_timeout(
                    f"local cluster timed out after {timeout:.0f}s waiting "
                    f"for process {pid}; all {n_processes} children "
                    f"stopped:", extra_lines=stalled)
            if proc.returncode == flight_recorder.WATCHDOG_EXIT_CODE:
                stalled.append(f"process {pid} stalled (flight-recorder "
                               f"watchdog, gang deadline "
                               f"{gang_deadline}s): "
                               f"{self.stderr_tail(pid, limit=400)}")
                # one stalled member wedges every sibling on its
                # collectives and the gang is going to raise regardless —
                # stop the rest instead of letting them burn the
                # remaining wall clock.  But the siblings are stalled on
                # the SAME exchange: their own watchdogs fire within ~a
                # poll interval of this one, so first give every
                # still-alive sibling one short window to write its black
                # box (killed pre-dump = no thread stacks for that child,
                # and per-child dumps are the point)
                rest = procs[pid + 1:]
                if rest:
                    grace_deadline = time.monotonic() + min(
                        5.0, gang_deadline or 5.0)
                    while time.monotonic() < grace_deadline and any(
                            p.poll() is None and not os.path.exists(
                                os.path.join(workdir, f"flight_{q}.jsonl"))
                            for q, p in enumerate(rest, start=pid + 1)):
                        time.sleep(0.05)
                    time.sleep(0.2)     # let an in-flight dump write finish
                    errors.extend(
                        f"stopped after sibling stall: {tail}"
                        for tail in _terminate_then_kill(
                            rest, first_pid=pid + 1,
                            tail_fn=self.stderr_tail))
                break
            elif proc.returncode != 0:
                errors.append(f"process {pid} rc={proc.returncode}: "
                              f"{self.stderr_tail(pid)}")
            elif os.path.exists(self.out_paths[pid]):
                with open(self.out_paths[pid], "rb") as f:
                    results.append(pickle.load(f))
        if stalled:
            # one stalled member wedges the whole gang (collectives
            # block); siblings usually die of the same watchdog — report
            # them all, with every child's black box attached
            dumps = _collect_flight_dumps(workdir, n_processes)
            err = ClusterStallError(
                "local cluster stalled:\n" + "\n".join(stalled + errors)
                + "\n" + _dump_summary(dumps))
            err.flight_dumps = dumps
            raise err
        if errors:
            dumps = _collect_flight_dumps(workdir, n_processes)
            err = RuntimeError("local cluster failed:\n" + "\n".join(errors))
            err.flight_dumps = dumps
            raise err
        return results


def _spawn_once(fn: Callable, n_processes: int, port: int,
                local_devices: int, timeout: float,
                extra_env: Optional[dict],
                gang_deadline: Optional[float],
                gang_fires: int = 1,
                remote_ui: Optional[str] = None) -> list:
    return GangHandle(fn, n_processes, port, local_devices=local_devices,
                      timeout=timeout, extra_env=extra_env,
                      gang_deadline=gang_deadline, gang_fires=gang_fires,
                      remote_ui=remote_ui).wait()


def spawn_local_cluster(fn: Callable, n_processes: int = 2, port: int = 12655,
                        local_devices: int = 1, timeout: float = 120.0,
                        extra_env: Optional[dict] = None,
                        startup_retries: int = 2,
                        gang_deadline: Optional[float] = None,
                        remote_ui: Optional[str] = None) -> list:
    """Run ``fn(process_index, process_count)`` in N fresh local processes
    under a real jax.distributed runtime (CPU, loopback).  Returns each
    process's pickled return value.  ``fn`` must be picklable (module-level
    function).  This is the test rig for launcher/checkpoint/fault-
    tolerance paths — the DummyTransport translation.

    Resilience: a gang member that never joins gets the WHOLE gang
    terminated (then killed) and the error carries every child's stderr
    tail; startup flakes (stale coordinator port, racing binds) retry up
    to ``startup_retries`` times on a shifted port with backoff
    (``resilience.retry``, site ``launcher.spawn``).

    Flight recorder: every child dumps a black box (thread stacks, the
    last N spans/events, metric snapshot) on crash or SIGTERM.
    ``gang_deadline`` additionally arms a per-child stall watchdog: a
    child whose instrumented sites (``trainer.step``, ``dcn.exchange``,
    ...) make no progress for that long dumps its box and exits, and
    the raised :class:`ClusterStallError` / :class:`ClusterTimeoutError`
    carries every child's parsed dump as ``.flight_dumps`` — the next
    rc=124 is a per-host stall report, not silence.  When not passed,
    the deadline defaults to half the wall budget with one grace fire
    (first dead deadline dumps + re-arms; the second exits 87 still
    inside ``timeout``), so a legitimately slow XLA compile between
    stamps never kills a healthy gang; an explicit ``gang_deadline``
    is one-strike.  The watchdog arms on a child's FIRST progress
    stamp, so workers that never touch an instrumented site are only
    bounded by ``timeout``.  Pass ``gang_deadline=0`` to disable the
    watchdog.

    When tracing is active in the launching process, its span context is
    handed to every worker via ``DL4J_TPU_TRACE_CONTEXT`` — worker spans
    parent under the launcher's current span, so one Chrome trace shows
    the whole cluster.

    Telemetry federation: ``remote_ui`` (a coordinator ``UIServer`` URL,
    default: the launcher's own ``DL4J_TPU_REMOTE_UI``) is injected into
    every child as ``DL4J_TPU_REMOTE_UI`` plus a per-child
    ``DL4J_TPU_WORKER_ID`` (``w<pid>``); the child bootstrap installs a
    :class:`~deeplearning4j_tpu.obs.remote.RemoteStatsRouter`, so every
    gang member's steps, heartbeats and stats land on the coordinator's
    ``/cluster`` dashboard and ``worker``-labeled ``/metrics`` series."""
    from deeplearning4j_tpu.resilience.retry import RetryPolicy, with_retries
    if remote_ui is None:
        remote_ui = os.environ.get("DL4J_TPU_REMOTE_UI") or None
    gang_fires = 1
    if gang_deadline is None:
        # silently-armed default: half the wall budget with ONE grace
        # fire, so a child whose XLA compile legitimately outlives one
        # deadline costs a spurious dump, not the gang — a genuine stall
        # still exits 87 at 2×deadline, inside the wall clock.  Callers
        # who pass an explicit deadline asked for one-strike semantics.
        gang_deadline = max(5.0, (timeout - 15.0) / 2.0)
        gang_fires = 2
    elif gang_deadline <= 0:
        gang_deadline = None
    attempt = {"n": 0}

    def _once():
        i = attempt["n"]
        attempt["n"] += 1
        # a fresh port per retry: the usual flake is the previous gang's
        # coordinator socket lingering in TIME_WAIT
        return _spawn_once(fn, n_processes, port + i * 97, local_devices,
                           timeout, extra_env, gang_deadline, gang_fires,
                           remote_ui=remote_ui)

    policy = RetryPolicy(max_attempts=1 + max(0, startup_retries),
                         base_delay_s=0.2, jitter=0.0,
                         retryable=_is_startup_flake)
    return with_retries(_once, policy=policy, site="launcher.spawn")
