"""Multi-host SPMD bootstrap — the Spark-orchestration replacement.

Parity with the reference's cluster story (SURVEY.md §2.7/§3.4: Spark
driver broadcasts the model, launches one long-lived worker per executor,
Aeron mesh forms via driver handshake): on TPU pods the runtime IS the
cluster — one process per host, ``jax.distributed.initialize`` handshakes
with the coordinator, and every jit'd step runs gang-scheduled SPMD.

Also provides the multi-process CPU test rig (DummyTransport parity,
SURVEY.md §4.2): spawn N local processes over loopback with
``spawn_local_cluster`` and run a function under a real multi-process
``jax.distributed`` runtime without any TPU pod.
"""

from __future__ import annotations

import os
import pickle
import subprocess
import sys
import tempfile
from typing import Callable, Optional


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """``jax.distributed.initialize`` with env-var fallbacks
    (DL4J VoidConfiguration's controller address/ports equivalent).
    No-ops on single-process runs."""
    import jax
    from deeplearning4j_tpu.obs import tracing
    coordinator_address = coordinator_address or os.environ.get("DL4J_TPU_COORDINATOR")
    if num_processes is None:
        num_processes = int(os.environ.get("DL4J_TPU_NUM_PROCESSES", "1"))
    if process_id is None:
        process_id = int(os.environ.get("DL4J_TPU_PROCESS_ID", "0"))
    if num_processes <= 1:
        return
    with tracing.span("distributed_init", processes=num_processes,
                      process_id=process_id):
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)


_WORKER_TEMPLATE = r"""
import os, pickle, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count={local_devices}")
import jax
jax.config.update("jax_platforms", "cpu")  # sitecustomize may pin a TPU platform
jax.distributed.initialize(coordinator_address="127.0.0.1:{port}",
                           num_processes={n}, process_id={pid})
with open({fn_path!r}, "rb") as f:
    fn = pickle.load(f)
result = fn(jax.process_index(), jax.process_count())
with open({out_path!r}, "wb") as f:
    pickle.dump(result, f)
"""


def spawn_local_cluster(fn: Callable, n_processes: int = 2, port: int = 12655,
                        local_devices: int = 1, timeout: float = 120.0,
                        extra_env: Optional[dict] = None) -> list:
    """Run ``fn(process_index, process_count)`` in N fresh local processes
    under a real jax.distributed runtime (CPU, loopback).  Returns each
    process's pickled return value.  ``fn`` must be picklable (module-level
    function).  This is the test rig for launcher/checkpoint/fault-
    tolerance paths — the DummyTransport translation.

    When tracing is active in the launching process, its span context is
    handed to every worker via ``DL4J_TPU_TRACE_CONTEXT`` — worker spans
    parent under the launcher's current span, so one Chrome trace shows
    the whole cluster."""
    from deeplearning4j_tpu.obs import tracing
    workdir = tempfile.mkdtemp(prefix="dl4j_tpu_cluster_")
    fn_path = os.path.join(workdir, "fn.pkl")
    with open(fn_path, "wb") as f:
        pickle.dump(fn, f)
    procs = []
    out_paths = []
    trace_env = tracing.propagation_env()
    for pid in range(n_processes):
        out_path = os.path.join(workdir, f"out_{pid}.pkl")
        out_paths.append(out_path)
        script = _WORKER_TEMPLATE.format(n=n_processes, pid=pid, port=port,
                                         fn_path=fn_path, out_path=out_path,
                                         local_devices=local_devices)
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)  # template sets its own
        env.update(trace_env)
        if extra_env:
            env.update(extra_env)
        procs.append(subprocess.Popen([sys.executable, "-c", script], env=env,
                                      stdout=subprocess.PIPE,
                                      stderr=subprocess.PIPE))
    results = []
    errors = []
    for pid, proc in enumerate(procs):
        try:
            _, stderr = proc.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            proc.kill()
            errors.append(f"process {pid} timed out")
            continue
        if proc.returncode != 0:
            errors.append(f"process {pid} rc={proc.returncode}: "
                          f"{stderr.decode()[-800:]}")
        elif os.path.exists(out_paths[pid]):
            with open(out_paths[pid], "rb") as f:
                results.append(pickle.load(f))
    if errors:
        raise RuntimeError("local cluster failed:\n" + "\n".join(errors))
    return results
