"""Data-parallel training — ParallelWrapper / SharedTrainingMaster parity.

The reference's three DP strategies (SURVEY.md §2.7):
  1. ``ParallelWrapper`` (single node, per-GPU threads, param averaging or
     encoded gradient sharing via shared-memory accumulator),
  2. ``ParameterAveragingTrainingMaster`` (Spark, periodic tree-aggregate),
  3. ``SharedTrainingMaster`` (Spark + Aeron async threshold-encoded push)
are all subsumed by ONE synchronous construct: batch sharded over the
``data`` mesh axis, parameters replicated, gradient psum emitted by GSPMD
inside the jit step, allreduce riding ICI.  BASELINE.json authorizes
exactly this swap (dense sync allreduce ≫ sparse async codec on-chip).

``ParallelWrapper`` here keeps the reference's class name and fit()
surface but is a thin shell: sharding + the SAME jit train step the
single-chip Trainer builds.  Exact parameter-averaging parity (average
every N steps instead of every step) is available via
``averaging_frequency > 1`` — gradients then apply locally per shard and
params re-sync by periodic mean, which is semantically what
ParameterAveragingTrainingMaster does; the default (1) is the stronger
every-step allreduce.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel import mesh as mesh_mod
from deeplearning4j_tpu.train.trainer import Trainer


class ParallelWrapper(Trainer):
    """Drop-in DP trainer: same ``fit(iterator, epochs)`` surface as
    Trainer, executing each step across the mesh's ``data`` axis.

    The global batch from the iterator is split across devices (its
    leading dim must be divisible by the data-axis size).
    """

    def __init__(self, net, mesh: Optional[Mesh] = None, listeners=None,
                 averaging_frequency: int = 1):
        super().__init__(net, listeners=listeners)
        self.mesh = mesh if mesh is not None else mesh_mod.make_mesh()
        self.averaging_frequency = max(1, averaging_frequency)
        self._placed = False
        if self.averaging_frequency != 1:
            raise NotImplementedError(
                "averaging_frequency > 1 (ParameterAveraging parity mode) "
                "requires the per-shard updater state machinery; the default "
                "every-step psum allreduce is the supported (and stronger) mode")

    def _ensure_ready(self):
        super()._ensure_ready()
        if not self._placed:
            net = self.net
            net.params_ = mesh_mod.replicate(self.mesh, net.params_)
            net.state_ = mesh_mod.replicate(self.mesh, net.state_)
            net.opt_state = mesh_mod.replicate(self.mesh, net.opt_state)
            self._placed = True

    def fit_batch(self, batch, rng) -> float:
        """Shard the batch over ``data``, then run the ordinary jit step —
        GSPMD partitions the forward/backward and inserts the gradient
        psum over ICI automatically (params are replicated, so their
        gradient must be allreduced to stay consistent)."""
        import dataclasses as _dc
        self._ensure_ready()
        sharded = _dc.replace(
            batch,
            features=mesh_mod.shard_batch(self.mesh, batch.features),
            labels=mesh_mod.shard_batch(self.mesh, batch.labels),
            features_mask=mesh_mod.shard_batch(self.mesh, batch.features_mask),
            labels_mask=mesh_mod.shard_batch(self.mesh, batch.labels_mask),
        )
        return super().fit_batch(sharded, rng)
