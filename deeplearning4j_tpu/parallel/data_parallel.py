"""Deprecated shim — data parallelism is a layout on the unified mesh.

.. deprecated::
    ``ParallelWrapper``'s default (every-step allreduce) mode is exactly
    ``Trainer(layout="dp<N>")`` — batch sharded over ``data``, params
    replicated, gradient psum emitted by GSPMD inside the one donated
    jit step — and this class is now a thin subclass that passes its
    mesh straight to the unified Trainer flag (docs/PARALLELISM.md).
    It survives for the reference's class name (DL4J ``ParallelWrapper``
    / the Spark TrainingMasters), for the parameter-averaging parity
    mode (``averaging_frequency > 1``: per-shard divergent replicas,
    periodic mean resync — semantics no single jit layout expresses),
    and for ZeRO-1 updater-state sharding.  New code calls
    ``Trainer(net, layout=...)``.
"""

from __future__ import annotations

import warnings
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.obs import tracing
from deeplearning4j_tpu.obs.registry import get_registry
from deeplearning4j_tpu.parallel import mesh as mesh_mod
from deeplearning4j_tpu.parallel.mesh import AXIS_DATA, DATA_AXES  # noqa: F401  (canonical home: mesh.py)
from deeplearning4j_tpu.train import step_cache
from deeplearning4j_tpu.train.trainer import Trainer

warnings.warn(
    "deeplearning4j_tpu.parallel.data_parallel is deprecated; use "
    "Trainer(layout='dp<N>') — ParallelWrapper remains as a thin shim "
    "over the unified mesh path (docs/PARALLELISM.md)",
    DeprecationWarning, stacklevel=2)


class ParallelWrapper(Trainer):
    """Drop-in DP trainer: same ``fit(iterator, epochs)`` surface as
    Trainer, executing each step across the mesh's ``data`` axis.

    The global batch from the iterator is split across devices (its
    leading dim must be divisible by the data-axis size).

    Default mode routes through the unified layout path
    (``Trainer(mesh=...)``); ``averaging_frequency > 1`` keeps the
    ParameterAveragingTrainingMaster parity machinery (stacked divergent
    replicas, periodic mean) that no single-program layout expresses.
    """

    def __init__(self, net, mesh: Optional[Mesh] = None, listeners=None,
                 averaging_frequency: int = 1, average_updater_state: bool = True,
                 zero_optimizer_sharding: bool = False):
        self.mesh = mesh if mesh is not None else mesh_mod.make_mesh()
        self.averaging_frequency = max(1, averaging_frequency)
        self.average_updater_state = average_updater_state
        self.zero_optimizer_sharding = zero_optimizer_sharding
        if zero_optimizer_sharding and averaging_frequency > 1:
            raise ValueError("zero_optimizer_sharding requires the "
                             "every-step allreduce mode (averaging_frequency=1)")
        if self.averaging_frequency == 1:
            # the unified path IS the old default mode: batch sharded
            # over 'data', params replicated, GSPMD gradient psum
            super().__init__(net, listeners=listeners, mesh=self.mesh)
        else:
            # averaging mode keeps its own placement (stacked replicas)
            super().__init__(net, listeners=listeners)
        self._placed = False
        self._steps_since_avg = 0
        self._avg_step = None
        self._avg_fn = None

    def _zero_shardings(self, opt_state):
        """ZeRO-1 placement: each optimizer-state tensor sharded over the
        ``data`` axis on its first divisible dim (scalars and indivisible
        leaves stay replicated).  Absent in the reference (pre-ZeRO era,
        SURVEY §2.7) — per-device updater memory drops ~n_data-fold for
        Adam-class updaters."""
        n = int(self.mesh.shape[AXIS_DATA])

        def spec(leaf):
            shape = getattr(leaf, "shape", ())
            for i, d in enumerate(shape):
                if d % n == 0 and d > 0:
                    return NamedSharding(
                        self.mesh, P(*([None] * i), AXIS_DATA))
            return NamedSharding(self.mesh, P())

        return jax.tree_util.tree_map(spec, opt_state)

    def _ensure_ready(self):
        if (self.zero_optimizer_sharding and
                self._opt_state_shardings is None):
            # opt_state must exist to derive shardings; build it the same
            # way the base class would, BEFORE the step is jitted
            if self.net.params_ is None:
                self.net.init()
            if self.net.opt_state is None:
                self.net.opt_state = self.tx.init(self.net.params_)
            self._opt_state_shardings = self._zero_shardings(self.net.opt_state)
        if self.averaging_frequency > 1 and not self._placed:
            net = self.net
            if net.params_ is None:
                net.init()
            if net.opt_state is None:
                net.opt_state = self.tx.init(net.params_)
            self._place_replicas()
            self._placed = True
        super()._ensure_ready()
        get_registry().gauge("tpudl_parallel_mesh_devices").set(
            int(self.mesh.shape[AXIS_DATA]))

    def _jit_step_fns(self) -> tuple:
        return super()._jit_step_fns() + (self._avg_step, self._avg_fn)

    def fit_batch(self, batch, rng, prepared: bool = False) -> float:
        """One DP step.

        ``averaging_frequency == 1`` (default): the unified layout path —
        params replicated, GSPMD partitions forward/backward and inserts
        the gradient psum over ICI automatically (the
        SharedTrainingMaster/ParallelWrapper gradient-sharing swap).

        ``averaging_frequency > 1``: ParameterAveragingTrainingMaster
        parity — each data shard trains LOCALLY (divergent per-shard
        replicas, zero cross-device traffic per step) and params (plus,
        optionally, updater state) re-sync by mean every N steps.
        """
        self._ensure_ready()
        if self.averaging_frequency > 1:
            return self._fit_batch_averaging(batch, rng)
        return super().fit_batch(batch, rng, prepared=prepared)

    def _fit_tbptt(self, batch, rng, prepared: bool = False):
        if self.averaging_frequency > 1:
            raise NotImplementedError(
                "tBPTT with averaging_frequency > 1 is not supported — use "
                "the default every-step allreduce (averaging_frequency=1)")
        return super()._fit_tbptt(batch, rng, prepared=prepared)

    def fit(self, iterator, epochs: int = 1, resume_from=None):
        result = super().fit(iterator, epochs, resume_from=resume_from)
        if self.averaging_frequency > 1:
            self._finalize_averaging()
        return result

    # ------------------------------------------------ param-averaging mode
    def _n_shards(self) -> int:
        return int(self.mesh.shape[AXIS_DATA])

    def _place_replicas(self):
        """Stack per-shard replicas on a new leading axis sharded over
        ``data`` — each device owns one divergent copy."""
        net = self.net
        n = self._n_shards()

        def stack(tree):
            return jax.tree_util.tree_map(
                lambda a: jnp.broadcast_to(a[None], (n,) + a.shape), tree)

        net.params_ = mesh_mod.shard_batch(self.mesh, stack(net.params_))
        net.state_ = mesh_mod.shard_batch(self.mesh, stack(net.state_))
        net.opt_state = mesh_mod.shard_batch(self.mesh, stack(net.opt_state))

    def _fit_batch_averaging(self, batch, rng):
        from deeplearning4j_tpu.train.trainer import make_loss_fn
        net = self.net
        n = self._n_shards()
        if self._avg_step is None:
            def build_avg_step():
                loss_fn = make_loss_fn(net)
                tx = self.tx

                def local_step(params, state, opt_state, features, labels,
                               features_mask, labels_mask, rng):
                    (loss, new_state), grads = jax.value_and_grad(
                        loss_fn, has_aux=True)(params, state, features, labels,
                                               features_mask, labels_mask, rng)
                    updates, opt_state = tx.update(grads, opt_state, params)
                    params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
                    return params, new_state, opt_state, loss

                # vmap over the replica axis: leading dim is sharded over
                # 'data', so XLA partitions this with no collectives at all
                return jax.jit(jax.vmap(local_step), donate_argnums=(0, 1, 2))

            def build_avg_fn():
                @jax.jit
                def avg(tree):
                    return jax.tree_util.tree_map(
                        lambda a: jnp.broadcast_to(jnp.mean(a, axis=0), a.shape),
                        tree)
                return avg

            key = self._step_key(f"dp_avg_{n}")
            self._avg_step = step_cache.get_or_build(key, build_avg_step)
            self._avg_fn = step_cache.get_or_build(
                None if key is None else key + ("mean",), build_avg_fn)

        def split_leading(v):
            if v is None:
                return None
            a = jnp.asarray(v)
            return mesh_mod.shard_batch(
                self.mesh, a.reshape((n, a.shape[0] // n) + a.shape[1:]))

        rngs = jax.random.split(rng, n)
        fmask = getattr(batch, "features_mask", None)
        if fmask is None:
            fmask = getattr(batch, "features_masks", None)
        lmask = getattr(batch, "labels_mask", None)
        if lmask is None:
            lmask = getattr(batch, "labels_masks", None)
        params, state, opt_state, losses = self._avg_step(
            net.params_, net.state_, net.opt_state,
            split_leading(batch.features), split_leading(batch.labels),
            split_leading(fmask), split_leading(lmask), rngs)
        net.params_, net.state_, net.opt_state = params, state, opt_state
        self._steps_since_avg += 1
        if self._steps_since_avg >= self.averaging_frequency:
            with tracing.span("average", shards=n,
                              frequency=self.averaging_frequency):
                net.params_ = self._avg_fn(net.params_)
                if self.average_updater_state:
                    net.opt_state = self._avg_fn(net.opt_state)
            get_registry().counter("tpudl_parallel_avg_syncs_total").inc()
            self._steps_since_avg = 0
        from deeplearning4j_tpu.config import get_config
        from deeplearning4j_tpu.obs.profiler import check_finite
        cfg = get_config()
        if cfg.nan_panic or cfg.inf_panic:
            check_finite(net.params_, "params after averaging step")
        return jnp.mean(losses)

    def _finalize_averaging(self):
        """Collapse the stacked replica axis back to a plain usable model
        (DL4J's ParameterAveragingTrainingMaster hands back the averaged
        net): average across shards, take one copy, reset placement."""
        net = self.net
        if self._steps_since_avg:
            net.params_ = self._avg_fn(net.params_)
            if self.average_updater_state:
                net.opt_state = self._avg_fn(net.opt_state)
            self._steps_since_avg = 0

        def unstack(tree):
            return jax.tree_util.tree_map(lambda a: a[0], tree)

        net.params_ = unstack(net.params_)
        net.state_ = unstack(net.state_)
        net.opt_state = unstack(net.opt_state)
        self._placed = False  # next fit() re-stacks
