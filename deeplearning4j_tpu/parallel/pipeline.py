"""Pipeline parallelism — microbatched stage execution over the ``stage``
mesh axis (named ``stage`` before the unified-mesh refactor).

Capability BEYOND the reference (SURVEY.md §2.7: no PP anywhere in DL4J).
GPipe-style schedule via ``shard_map`` + ``ppermute``: each device holds
one stage's params; activations flow to the neighbor after each
microbatch tick; the loop runs S + M - 1 ticks (S stages, M microbatches)
with bubble fraction (S-1)/(S+M-1).  Autodiff traces straight through
``ppermute``, so ``jax.grad`` of a pipelined forward gives the pipelined
backward for free — no hand-written 1F1B needed for correctness (1F1B
memory scheduling is a later optimization).

Usage: stage_fn(stage_params, x) must be shape-preserving [B_micro, ...] →
[B_micro, ...] (the homogeneous fast path — one switch-free program).
Heterogeneous stages (per-stage param pytrees, non-uniform widths) and
the memory-bounded 1F1B schedule live in
:mod:`deeplearning4j_tpu.parallel.pipeline_stages`, which pipelines real
models (BERT as embeddings/encoder/head stages).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from deeplearning4j_tpu.parallel.mesh import AXIS_PIPE
from deeplearning4j_tpu.utils.jax_compat import pcast, shard_map


def pipeline_apply(stage_fn: Callable, stage_params, x: jnp.ndarray,
                   mesh: Mesh, n_microbatches: int, axis: str = AXIS_PIPE,
                   data_axis: str | None = None):
    """Run a homogeneous S-stage pipeline.

    - ``stage_params``: pytree whose leaves have a leading stage dim S,
      sharded over ``axis`` (each device sees its own stage's slice).
    - ``x``: global batch [B, ...]; split into M = n_microbatches chunks.
      All data enters at stage 0 and exits at stage S-1.
    - ``data_axis``: optional second mesh axis for dp×pp — the batch is
      additionally sharded over it (each data-parallel pipeline replica
      runs the schedule on its own batch shard; stage params replicate
      across ``data_axis``).

    Returns y [B, ...] (the last stage's outputs, gathered).
    """
    from deeplearning4j_tpu.obs import tracing
    from deeplearning4j_tpu.obs.registry import get_registry
    n_stages = mesh.shape[axis]
    data_par = mesh.shape[data_axis] if data_axis else 1
    if x.shape[0] % (n_microbatches * data_par):
        raise ValueError(f"batch {x.shape[0]} not divisible by "
                         f"microbatches*data_par={n_microbatches * data_par}")

    def local(params, x_local):
        # params: this stage's slice (leading dim 1) → squeeze
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        idx = lax.axis_index(axis)
        micro = x_local.reshape((n_microbatches, -1) + x_local.shape[1:])
        n_ticks = n_stages + n_microbatches - 1
        # carry buffers are device-varying (each stage holds different acts)
        buf = pcast(jnp.zeros_like(micro[0]), (axis,), to="varying")
        outs = pcast(jnp.zeros_like(micro), (axis,), to="varying")

        def tick(carry, t):
            buf, outs = carry
            # stage 0 injects microbatch t (if available) — others use buf
            inject = jnp.where(t < n_microbatches, t, 0)
            x_in = jnp.where(idx == 0,
                             micro[inject],
                             buf)
            y = stage_fn(params, x_in)
            # last stage records its result for microbatch (t - (S-1))
            out_slot = t - (n_stages - 1)
            valid = (idx == n_stages - 1) & (out_slot >= 0) & (out_slot < n_microbatches)
            slot = jnp.clip(out_slot, 0, n_microbatches - 1)
            outs = outs.at[slot].set(jnp.where(valid, y, outs[slot]))
            # pass activations to next stage (ring; last→0 wraps but stage 0
            # ignores the incoming buffer)
            perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
            buf = lax.ppermute(y, axis, perm)
            return (buf, outs), None

        (buf, outs), _ = lax.scan(tick, (buf, outs), jnp.arange(n_ticks))
        return outs.reshape((-1,) + x_local.shape[1:])

    # params sharded by stage; x replicated in (each stage needs only its
    # ticks but replication keeps the schedule simple); out taken from the
    # last stage — psum_scatter not needed since only one stage wrote it.
    param_spec = jax.tree_util.tree_map(lambda _: P(axis), stage_params)
    x_spec = P(data_axis) if data_axis else P()
    out_spec = P((axis, data_axis)) if data_axis else P(axis)
    # span covers build+dispatch on the host (under an outer jit this is
    # trace-time only, which is exactly when the schedule cost is paid)
    with tracing.span("pipeline", stages=int(n_stages),
                      microbatches=n_microbatches,
                      data_parallel=int(data_par)):
        get_registry().counter("tpudl_parallel_pipeline_calls_total").inc()
        y = shard_map(local, mesh=mesh,
                      in_specs=(param_spec, x_spec),
                      out_specs=out_spec)(stage_params, x)  # each stage emits its block
    # keep only the LAST stage's block (others are zeros): [S*B] → [B]
    b = x.shape[0]
    return y[(n_stages - 1) * b:]
