"""Distributed training & parallelism — ONE unified mesh, composable
layouts (the north-star replacement for the reference's three-transport
stack: Spark TCP orchestration + Aeron UDP parameter-server mesh + JNI
threshold codecs — SURVEY.md §2.7/§3.4).

On TPU the whole pyramid collapses into compiler-scheduled collectives
over ICI/DCN inside jit-compiled programs, expressed as PartitionSpec
layouts over one ``jax.sharding.Mesh``:

- ``mesh``     — THE single source of truth: axis constants
                 (``AXIS_DATA``/``AXIS_MODEL``/``AXIS_PIPE``/``AXIS_SEQ``/
                 ``AXIS_EXPERT``), ``MeshSpec`` (parseable layout sizes,
                 ``"dp2xtp2xpp2"``), ``MeshLayout`` (resolved layout +
                 per-layer-family TP rules + placement + collective-bytes
                 model + ``tpudl_mesh_*`` gauges), multi-slice/DCN aware.
- ``unified``  — the composable collectives (ring/Ulysses attention over
                 ``seq``, MoE all_to_all over ``expert``) and the 1F1B
                 step builder behind ``Trainer(layout="...pp...")``.
- ``pipeline`` / ``pipeline_stages`` — microbatched stage parallelism
                 over ``pipe`` (GPipe / heterogeneous 1F1B machinery).
- ``compression`` — threshold/bitmap gradient codec + residual
                 accumulator for the cross-slice DCN path.
- ``inference`` — ParallelInference parity shim over serve.InferenceEngine.
- ``launcher`` — multi-host SPMD bootstrap (jax.distributed).

Training selects a layout with ONE flag — ``Trainer(layout="dp2xtp2")``
— instead of choosing a sibling wrapper class.  The old per-mode entry
points (``data_parallel.ParallelWrapper``, ``tensor_parallel``,
``context_parallel``, ``expert_parallel``) are deprecation shims that
warn on import and route here (docs/PARALLELISM.md has the migration
table).
"""

from deeplearning4j_tpu.parallel.mesh import (
    AXIS_DATA, AXIS_EXPERT, AXIS_MODEL, AXIS_PIPE, AXIS_SEQ, MESH_AXES,
    MeshLayout, MeshSpec, make_mesh, resolve_layout)
from deeplearning4j_tpu.parallel.compression import (
    threshold_encode, threshold_decode, bitmap_encode, bitmap_decode,
    threshold_encode_device, threshold_decode_device,
    bitmap_encode_device, bitmap_decode_device,
    EncodedGradientsAccumulator,
)
from deeplearning4j_tpu.parallel.inference import ParallelInference
from deeplearning4j_tpu.parallel.unified import (
    moe_ffn, moe_ffn_dense, init_moe_params, shard_moe_params,
    ring_attention, ulysses_attention, reference_attention,
)

__all__ = [
    "AXIS_DATA", "AXIS_EXPERT", "AXIS_MODEL", "AXIS_PIPE", "AXIS_SEQ",
    "MESH_AXES", "make_mesh", "MeshSpec", "MeshLayout", "resolve_layout",
    "ParallelWrapper",
    "threshold_encode", "threshold_decode", "bitmap_encode", "bitmap_decode",
    "threshold_encode_device", "threshold_decode_device",
    "bitmap_encode_device", "bitmap_decode_device",
    "EncodedGradientsAccumulator", "ParallelInference",
    "moe_ffn", "moe_ffn_dense", "init_moe_params", "shard_moe_params",
    "ring_attention", "ulysses_attention", "reference_attention",
]


def __getattr__(name):
    # ParallelWrapper resolves lazily: its home module is a deprecation
    # shim that warns on import, and the package must not fire that
    # warning for users who never touch the legacy class
    if name == "ParallelWrapper":
        from deeplearning4j_tpu.parallel.data_parallel import ParallelWrapper
        return ParallelWrapper
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
