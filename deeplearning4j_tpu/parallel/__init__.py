"""Distributed training & parallelism — the north-star replacement for the
reference's three-transport stack (SURVEY.md §2.7/§3.4: Spark TCP
orchestration + Aeron UDP parameter-server mesh + JNI threshold codecs).

On TPU the whole pyramid collapses into compiler-scheduled collectives
over ICI/DCN inside jit-compiled programs:

- ``mesh``              — device mesh builder (axes data/model/seq/stage),
                          multi-slice/DCN aware (MeshOrganizer parity — the
                          tree-mesh bookkeeping is jax runtime's job now).
- ``data_parallel``     — DP trainer: batch sharded over ``data``, gradient
                          allreduce = psum emitted by GSPMD (ParallelWrapper
                          + SharedTrainingMaster/ParameterAveraging parity;
                          synchronous dense allreduce replaces the async
                          threshold-encoded Aeron path per BASELINE.json).
- ``tensor_parallel``   — NamedSharding rules for BERT-class models over
                          the ``model`` axis (capability beyond reference).
- ``context_parallel``  — sequence parallelism over the ``seq`` axis:
                          ring attention (shard_map + ppermute, online
                          softmax, optional Pallas flash inner kernel)
                          and Ulysses all_to_all head-resharding — both
                          beyond reference (SURVEY.md §5.7).
- ``pipeline``          — GPipe-style microbatched stage parallelism over
                          the ``stage`` axis (beyond reference).
- ``expert_parallel``   — mixture-of-experts FFN with all_to_all dispatch
                          over the ``expert`` axis (beyond reference).
- ``compression``       — threshold/bitmap gradient codec + residual
                          accumulator (EncodedGradientsAccumulator +
                          encodeThresholdP1..P3/encodeBitmap parity) for the
                          optional DCN path; C++ kernel in ``native/``.
- ``inference``         — ParallelInference parity: a compatibility shim
                          over ``serve.InferenceEngine`` micro-batching.
- ``launcher``          — multi-host SPMD bootstrap (jax.distributed),
                          replacing Spark orchestration.
"""

from deeplearning4j_tpu.parallel.mesh import make_mesh, MeshSpec
from deeplearning4j_tpu.parallel.data_parallel import ParallelWrapper
from deeplearning4j_tpu.parallel.compression import (
    threshold_encode, threshold_decode, bitmap_encode, bitmap_decode,
    threshold_encode_device, threshold_decode_device,
    bitmap_encode_device, bitmap_decode_device,
    EncodedGradientsAccumulator,
)
from deeplearning4j_tpu.parallel.inference import ParallelInference
from deeplearning4j_tpu.parallel.expert_parallel import (
    moe_ffn, moe_ffn_dense, init_moe_params, shard_moe_params,
)
from deeplearning4j_tpu.parallel.context_parallel import (
    ring_attention, ulysses_attention, reference_attention,
)

__all__ = [
    "make_mesh", "MeshSpec", "ParallelWrapper",
    "threshold_encode", "threshold_decode", "bitmap_encode", "bitmap_decode",
    "threshold_encode_device", "threshold_decode_device",
    "bitmap_encode_device", "bitmap_decode_device",
    "EncodedGradientsAccumulator", "ParallelInference",
    "moe_ffn", "moe_ffn_dense", "init_moe_params", "shard_moe_params",
    "ring_attention", "ulysses_attention", "reference_attention",
]
