#!/usr/bin/env python
"""Benchmark harness — prints ONE json line with the headline metric.

Headline (BASELINE.json): ResNet-50 ImageNet-shape training throughput in
images/sec/chip on the real TPU.  The reference publishes no number
(BASELINE.md), so ``vs_baseline`` is computed against the public
MLPerf-era proxy for the A100 comparison point named by the north star
(~2750 img/s bf16 on one A100 — marked as a proxy, not a reference-repo
measurement).

Runs on whatever platform jax selects (the driver runs it on TPU);
bfloat16 compute policy, synthetic data (no network), steady-state steps
timed after compile+warmup.
"""

import json
import os
import sys
import time

import numpy as np


A100_PROXY_IMG_PER_SEC = 2750.0  # public MLPerf-era proxy, see BASELINE.md

# v5e public peak numbers for utilization lines
V5E_PEAK_BF16_TFLOPS = 197.0
# measured r5 on THIS chip (axon tunnel): best sustained bf16 matmul rate
# over shapes {8192³, 16384×2048×16384, dependency-free and scan chains} =
# ~130 TFLOP/s — the silicon's demonstrated ceiling, 66% of nominal
V5E_MEASURED_MATMUL_TFLOPS = 130.0
V5E_HBM_GBPS = 819.0

def _timed_region(run, sync, steps, repeats=3):
    """Best-of-``repeats`` steady-state seconds/step.

    ``run()`` dispatches one step and returns a handle; ``sync`` forces a
    device→host transfer of that handle.  This is the one trustworthy
    fence on the experimental tunnel platform — ``block_until_ready``
    there measured dispatch-only and produced the phantom r2→r3 BERT
    "regression".  Best-of filters tunnel hiccups."""
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        handle = None
        for _ in range(steps):
            handle = run()
        sync(handle)
        best = min(best, (time.perf_counter() - t0) / steps)
    return best


# ResNet-50 224x224 training FLOPs/image, from XLA cost_analysis of the
# full donated train step at batch 256 (5.72 TFLOP / 256 images; includes
# fwd+bwd+Nesterov update) — see bench/PROFILE.md round-2 roofline
RESNET50_TRAIN_GFLOP_PER_IMG = 22.34
# ... and HBM bytes/image from the same analysis (344 MB/image)
RESNET50_TRAIN_MB_PER_IMG = 344.0


def _phase_spans(trainer, batch_ds, key, steps, warmup):
    """Run warmup + one short attribution pass under a pinned tracer,
    emitting the span taxonomy from docs/observability.md (``bench`` →
    ``compile`` / ``steps``/``host_dispatch``).  Returns (tracer, phase
    dict) — the dict is DERIVED from the spans, so the jsonl/Chrome
    exports and the printed breakdown come from one measurement.  This
    pass doubles as the headline run's warmup (compile + steady steps);
    the headline number itself still comes from ``_timed_region``'s
    best-of-repeats discipline, so the attribution pass is capped at a
    few steps to keep its extra device time negligible."""
    from deeplearning4j_tpu.obs import tracing

    steps = min(steps, 4)
    tracer = tracing.Tracer(enabled=True)
    with tracing.use_tracer(tracer):
        with tracing.span("bench", steps=steps):
            with tracing.span("compile"):
                # first call traces+compiles the whole donated train step
                tracing.device_sync(trainer.fit_batch(batch_ds, key))
            for _ in range(max(warmup - 1, 0)):
                float(trainer.fit_batch(batch_ds, key))
            with tracing.span("steps", n=steps) as sp:
                handle = None
                with tracing.span("host_dispatch"):
                    for _ in range(steps):
                        handle = trainer.fit_batch(batch_ds, key)
                tracing.device_sync(handle)   # device wait lands on sp

    compile_s = sum(s.duration_s for s in tracer.find("compile"))
    host_s = sum(s.duration_s for s in tracer.find("host_dispatch"))
    measured = tracer.find("steps")
    wall_s = sum(s.duration_s for s in measured)
    sync_s = sum(s.device_sync_s for s in measured)
    phases = {
        "compile_s": round(compile_s, 3),
        "host_dispatch_ms_per_step": round(1e3 * host_s / steps, 3),
        "device_wait_ms_per_step": round(1e3 * sync_s / steps, 3),
        "wall_ms_per_step": round(1e3 * wall_s / steps, 3),
        "note": ("host = python+dispatch; device wait = post-dispatch "
                 "sync; execute/step ~= wall - host (async dispatch "
                 "keeps the device busy across steps)"),
    }
    return tracer, phases


def bench_resnet50(batch: int = 256, image: int = 224, steps: int = 12,
                   warmup: int = 2) -> dict:
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.config import DTypePolicy, set_dtype_policy
    from deeplearning4j_tpu.models import resnet50
    from deeplearning4j_tpu.obs.registry import get_registry
    from deeplearning4j_tpu.train.trainer import Trainer
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.train import Nesterovs

    set_dtype_policy(DTypePolicy.bf16())
    net = resnet50(height=image, width=image, num_classes=1000,
                   updater=Nesterovs(0.1, 0.9))
    net.init()
    trainer = Trainer(net)

    rng = np.random.default_rng(0)
    x = rng.uniform(0, 1, size=(batch, image, image, 3)).astype(np.float32)
    y = np.eye(1000, dtype=np.float32)[rng.integers(0, 1000, batch)]
    batch_ds = DataSet(jnp.asarray(x), jnp.asarray(y))
    key = jax.random.key(0)

    # warmup (compile) + phase attribution ride the same tracer
    tracer, phases = _phase_spans(trainer, batch_ds, key, steps, warmup)
    # the warmup queued the step's background cost analysis — a REAL
    # duplicate XLA compile that would contend with the very steps it
    # grades; let it land before entering the measured region (generous
    # timeout: a ResNet-50 TPU compile outlives drain's 60s default)
    from deeplearning4j_tpu.obs import costmodel
    costmodel.drain(timeout_s=300.0)
    step_s = _timed_region(lambda: trainer.fit_batch(batch_ds, key),
                           float, steps)
    get_registry().histogram("tpudl_bench_step_seconds").observe(step_s)
    trace_path = os.environ.get("DL4J_TPU_BENCH_TRACE")
    if trace_path:
        tracer.export_chrome_trace(trace_path)
        phases["chrome_trace"] = trace_path
    dt = step_s * steps
    img_per_sec = batch * steps / dt
    n_chips = max(len(jax.devices()), 1)
    per_chip = img_per_sec / n_chips
    # utilization lines from the MEASURED program: the trainer's cost
    # model pulled FLOPs/bytes from the compiled step's cost_analysis;
    # feed it the bench's own best-of step time so mfu/hbm_util come
    # from the compiler's accounting, not hand-derived constants
    costmodel.observe_step(trainer._last_step_fn, step_s,
                           sig=getattr(trainer, "_last_step_sig", None))
    perf = costmodel.bench_detail() or {}
    # hand-derived fallback lines kept for cross-checking the model
    mfu_proxy = (per_chip * RESNET50_TRAIN_GFLOP_PER_IMG / 1e3
                 / V5E_PEAK_BF16_TFLOPS)
    hbm = per_chip * RESNET50_TRAIN_MB_PER_IMG / 1e3
    return {
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / A100_PROXY_IMG_PER_SEC, 4),
        "detail": {
            "batch": batch, "image": image, "steps": steps,
            "step_time_ms": round(1000 * dt / steps, 2),
            "phases": phases,
            "mfu": perf.get("mfu", round(mfu_proxy, 3)),
            "hbm_util": perf.get("hbm_util"),
            "arith_intensity": perf.get("arith_intensity"),
            "perf": perf,
            "mfu_hand_proxy": round(mfu_proxy, 3),
            "hbm_gbps_sustained": round(hbm, 1),
            "hbm_roof_fraction": round(hbm / V5E_HBM_GBPS, 3),
            "device": str(jax.devices()[0]),
            "baseline_note": "A100 bf16 public proxy (~2750 img/s); reference repo publishes no number",
        },
    }


def bench_bert_mlm(batch: int = 32, seq_len: int = 128, steps: int = 30,
                   warmup: int = 3, repeats: int = 3) -> dict:
    """BERT-base MLM fine-tune step time — the second headline metric
    (BASELINE.json config #4: SameDiff TF-import BERT-base MLM).

    Timing discipline: see ``_timed_region``."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.config import DTypePolicy, set_dtype_policy
    from deeplearning4j_tpu.models.bert import BertConfig, BertForMaskedLM
    from deeplearning4j_tpu.train import Adam

    set_dtype_policy(DTypePolicy.bf16())
    # max_predictions: decode the vocab only at gathered masked positions
    # (TF BERT max_predictions_per_seq; 32 of 128 = 25%, safely above the
    # 15% masking rate) — FLOP accounting below credits the decode for
    # the gathered positions only
    config = dataclasses.replace(BertConfig.base(), max_predictions=32)
    model = BertForMaskedLM(config, seed=0)
    # bf16 first moment: −1.3 ms/step of mu HBM traffic; loss trajectory
    # agrees with f32-state Adam to ≤0.02 abs (≈0.3% rel) over 30 steps
    # (measured r5)
    tx = Adam(2e-5, mu_dtype="bf16").to_optax()
    opt_state = tx.init(model.params)
    step = model.make_train_step(tx)

    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, config.vocab_size, (batch, seq_len)), jnp.int32)
    labels = jnp.asarray(rng.integers(0, config.vocab_size, (batch, seq_len)), jnp.int32)
    weights = jnp.asarray((rng.random((batch, seq_len)) < 0.15), jnp.float32)
    attn = jnp.ones((batch, seq_len), jnp.float32)
    # rbg = the TPU-accelerated generator the model uses for dropout
    key = jax.random.key(0, impl="rbg")

    params, opt = model.params, opt_state
    n_params = model.num_params()
    for _ in range(warmup):
        params, opt, loss = step(params, opt, ids, labels, weights, attn, key)
    jax.device_get(loss)
    state = [params, opt]

    def run():
        state[0], state[1], loss = step(state[0], state[1], ids, labels,
                                        weights, attn, key)
        return loss

    step_s = _timed_region(run, jax.device_get, steps, repeats)
    # measured roofline stamp: FLOPs/bytes from the compiled step's own
    # cost_analysis (the analytic 6PT estimate below stays as the
    # cross-check the estimate-vs-compiler gap is judged by)
    from deeplearning4j_tpu.obs import costmodel
    perf = costmodel.measure(
        step, costmodel.abstractify((state[0], state[1], ids, labels,
                                     weights, attn, key)),
        step_s, kind="bench:bert_mlm") or {}
    # transformer train FLOPs ≈ 6·P·tokens + attention 12·L·T²·H·Dh·3
    # (fwd+bwd); the 6PT term dominates at seq 128.  The word-embedding
    # table's matmul is the MLM decode — credited only for the positions
    # it actually decodes (max_predictions gather), not the full width.
    tokens = batch * seq_len
    emb_params = config.vocab_size * config.hidden_size
    decode_tokens = (batch * config.max_predictions
                     if config.max_predictions else tokens)
    attn_flops = (12 * config.num_layers * batch * seq_len ** 2
                  * config.hidden_size)
    flops = (6.0 * (n_params - emb_params) * tokens
             + 6.0 * emb_params * decode_tokens + attn_flops)
    return {"step_time_ms": round(1000 * step_s, 2),
            "batch": batch, "seq_len": seq_len,
            "max_predictions": config.max_predictions,
            "tflops_per_step": round(flops / 1e12, 2),
            "mfu": perf.get("mfu", round(
                flops / step_s / 1e12 / V5E_PEAK_BF16_TFLOPS, 3)),
            "hbm_util": perf.get("hbm_util"),
            "arith_intensity": perf.get("arith_intensity"),
            "mfu_analytic": round(
                flops / step_s / 1e12 / V5E_PEAK_BF16_TFLOPS, 3),
            # nominal peak (197) is not reachable on this part: an 8192³
            # bf16 matmul (zero overhead, measured in-program via
            # lax.scan) sustains ~130 TFLOP/s — see bench/PROFILE.md
            # "measured matmul ceiling"; this reports utilization of the
            # silicon's demonstrated peak alongside nominal MFU
            "practical_peak_tflops": V5E_MEASURED_MATMUL_TFLOPS,
            "practical_peak_fraction": round(
                flops / step_s / 1e12 / V5E_MEASURED_MATMUL_TFLOPS, 3)}


def bench_bert_long_seq(seq_len: int = 4096, batch: int = 2,
                        steps: int = 5, warmup: int = 2) -> dict:
    """Long-sequence BERT MLM train step: Pallas flash kernel (fwd+bwd)
    vs the materializing einsum path (SURVEY §5.7 long-seq training)."""
    import dataclasses
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.config import DTypePolicy, set_dtype_policy
    from deeplearning4j_tpu.models import bert as bert_mod
    from deeplearning4j_tpu.train import Adam

    set_dtype_policy(DTypePolicy.bf16())
    base = bert_mod.BertConfig(vocab_size=30522, hidden_size=768,
                               num_layers=4, num_heads=12,
                               intermediate_size=3072, max_position=seq_len)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, base.vocab_size, (batch, seq_len)),
                      jnp.int32)
    labels = jnp.asarray(rng.integers(0, base.vocab_size, (batch, seq_len)),
                         jnp.int32)
    weights = jnp.asarray((rng.random((batch, seq_len)) < 0.15), jnp.float32)
    attn = jnp.ones((batch, seq_len), jnp.float32)
    key = jax.random.key(0, impl="rbg")

    out = {"seq_len": seq_len, "batch": batch, "num_layers": base.num_layers}
    n_params = None
    for name, cfg in (("einsum", base),
                      ("flash", dataclasses.replace(base, use_flash=True))):
        model = bert_mod.BertForMaskedLM(cfg, seed=0)
        n_params = model.num_params()
        tx = Adam(2e-5).to_optax()
        opt = tx.init(model.params)
        step = model.make_train_step(tx)
        params = model.params
        for _ in range(warmup):
            params, opt, loss = step(params, opt, ids, labels, weights,
                                     attn, key)
        jax.device_get(loss)
        state = [params, opt]

        def run():
            state[0], state[1], loss = step(state[0], state[1], ids, labels,
                                            weights, attn, key)
            return loss

        out[f"{name}_step_ms"] = round(
            _timed_region(run, jax.device_get, steps) * 1000, 2)
    out["flash_speedup"] = round(out["einsum_step_ms"]
                                 / out["flash_step_ms"], 2)
    flops = (6.0 * n_params * batch * seq_len
             + 12 * base.num_layers * batch * seq_len ** 2
             * base.hidden_size)
    out["tflops_per_step"] = round(flops / 1e12, 2)
    out["flash_mfu"] = round(
        flops / (out["flash_step_ms"] / 1e3) / 1e12 / V5E_PEAK_BF16_TFLOPS, 3)
    out["flash_practical_peak_fraction"] = round(
        flops / (out["flash_step_ms"] / 1e3) / 1e12
        / V5E_MEASURED_MATMUL_TFLOPS, 3)
    return out


def bench_dcn_multislice(steps: int = 6, batch: int = 32) -> dict:
    """Production multi-slice DCN training at ResNet-50 gradient scale
    (VERDICT r4 next #1 'done' row): wire-bytes ratio, D2H reduction,
    and per-step exchange overhead, sync vs overlapped.

    Both slices run on the ONE real chip (their compute serializes), so
    per-step DCN overhead = multislice_step − 2 × plain_step; the codec
    path (device encode → compact message → ring exchange → device
    decode+apply) is exactly the multi-process production path."""
    import time as _time

    import jax
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.models import resnet50
    from deeplearning4j_tpu.parallel.compression import (
        AdaptiveThresholdAlgorithm)
    from deeplearning4j_tpu.parallel.dcn_trainer import MultiSliceTrainer
    from deeplearning4j_tpu.train import Sgd, Trainer

    rng = np.random.default_rng(11)
    x = rng.uniform(0, 1, (batch, 32, 32, 3)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch)]
    data = DataSet(x, y)
    half = DataSet(x[:batch // 2], y[:batch // 2])

    def wall(fn, n):
        fn()                              # warm
        t0 = _time.monotonic()
        for _ in range(n):
            fn()
        return (_time.monotonic() - t0) / n

    # plain single-slice baseline at the same per-slice batch
    net0 = resnet50(height=32, width=32, num_classes=10,
                    updater=Sgd(0.01))
    net0.init()
    tr0 = Trainer(net0)
    key = jax.random.key(2)
    from deeplearning4j_tpu.obs import costmodel
    tr0.fit_batch(half, key)            # compile + queue cost analysis
    costmodel.drain(timeout_s=300.0)    # keep its duplicate compile out
    plain_s = wall(lambda: tr0.fit_batch(half, key), steps)

    out = {"grad_mb": None, "plain_step_ms": round(plain_s * 1e3, 2)}
    for overlap in (False, True):
        net = resnet50(height=32, width=32, num_classes=10,
                       updater=Sgd(0.01))
        net.init()
        # steady-state message capacity (the production default, 4× the
        # adaptive sparsity target ≈ 94k entries / 0.75 MB wire): the
        # dense warm-up transient is top-|v|-truncated by design, and τ
        # burns in over the warm-up steps below.  (A transient-sized
        # capacity of 4M entries = 32 MB/message was measured to drown
        # the row in this rig's tunnel D2H at ~70 ms/MB — real hardware
        # moves D2H ~100× faster, so tunnel transfer time would have
        # dominated the "overhead" being reported.)
        trainer = MultiSliceTrainer(
            net, n_slices=2, data_per_slice=1,
            devices=[jax.devices()[0]] * 2,
            device_encode=True, overlap=overlap,
            algorithm=AdaptiveThresholdAlgorithm(initial_threshold=1.0))
        try:
            for _ in range(6):      # τ burn-in toward the target sparsity
                trainer.fit_batch(data, key)
            costmodel.drain(timeout_s=300.0)   # codec analyses out of the region
            s = wall(lambda: trainer.fit_batch(data, key), steps)
            ws = trainer.last_wire_stats[0]
            out["grad_mb"] = round(ws["dense_bytes"] / 2 ** 20, 1)
            label = "overlap" if overlap else "sync"
            out[f"{label}_step_ms"] = round(s * 1e3, 2)
            out[f"{label}_overhead_ms"] = round((s - 2 * plain_s) * 1e3, 2)
            if not overlap:
                out["wire_bytes"] = ws["wire_bytes"]
                out["d2h_bytes"] = ws["d2h_bytes"]
                out["dense_bytes"] = ws["dense_bytes"]
                out["wire_ratio"] = round(
                    ws["dense_bytes"] / max(ws["wire_bytes"], 1), 1)
                out["d2h_reduction"] = round(
                    ws["dense_bytes"] / max(ws["d2h_bytes"], 1), 1)
        finally:
            trainer.close()
    out["note"] = ("2 slices share the one chip (compute serializes); "
                   "overhead = step - 2*plain_step and is DOMINATED by "
                   "this rig's tunnel device<->host link (~70 ms/MB; 4 "
                   "sub-MB transfers/step) — real-HW PCIe moves the "
                   "0.75 MB message in <1 ms; multi-process form "
                   "measured in tests/test_multiprocess.py over real TCP")
    return out


def bench_dp_scaling(measured_img_per_sec: float = 2242.0,
                     measured_step_ms: float = 114.0) -> dict:
    """DP scaling on the 8-device virtual CPU mesh (subprocess — the
    bench itself runs on the TPU platform) + the ICI communication model
    for the real v5e-8 slice (BASELINE workload #5)."""
    import json as _json
    import os
    import subprocess
    import sys as _sys

    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "bench", "dp_scaling.py")
    proc = subprocess.run([_sys.executable, script], capture_output=True,
                          text=True, timeout=900)
    lines = [ln for ln in proc.stdout.splitlines() if ln.startswith("{")]
    measured = _json.loads(lines[-1]) if lines else {
        "error": proc.stderr[-300:]}

    # ICI communication model for ResNet-50 DP on a v5e-8 slice:
    # f32 gradient allreduce, ring 2(n-1)/n factor, overlapped with the
    # backward pass (XLA latency-hiding scheduler).
    grad_mb = 25.58e6 * 4 / 1e6          # 102 MB of f32 gradients
    ring_mb = grad_mb * 2 * 7 / 8        # ring allreduce traffic, n=8
    ici_gbps = 180.0                     # ~per-chip usable ICI (v5e 2D torus,
                                         # 1600 Gbit/s aggregate, conservative)
    comm_ms = ring_mb / ici_gbps         # ≈ 1.0 ms, vs the measured step
    step_ms = measured_step_ms
    return {
        "cpu_mesh_measured": measured,
        "ici_model_v5e8": {
            "grad_bytes_mb": round(grad_mb, 1),
            "ring_allreduce_mb": round(ring_mb, 1),
            "assumed_ici_gbps": ici_gbps,
            "comm_ms_unoverlapped": round(comm_ms, 2),
            "comm_fraction_of_step": round(comm_ms / step_ms, 4),
            "projected_v5e8_img_per_sec": round(
                8 * measured_img_per_sec / (1 + comm_ms / step_ms), 0),
            "note": ("comm fully hideable behind bwd; projection assumes "
                     "no overlap (worst case) — scaling efficiency "
                     ">= 99% either way"),
        },
    }


def _bench_net_step(net, features, labels, steps=20, warmup=3, repeats=3):
    """Steady-state fit_batch time for a workload net (``_timed_region``
    discipline)."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.train.trainer import Trainer
    trainer = Trainer(net)
    batch = DataSet(jnp.asarray(features), jnp.asarray(labels))
    key = jax.random.key(0)
    for _ in range(warmup):
        loss = trainer.fit_batch(batch, key)
    float(loss)
    from deeplearning4j_tpu.obs import costmodel
    costmodel.drain()   # background cost analysis out of the timed region
    return round(_timed_region(lambda: trainer.fit_batch(batch, key),
                               float, steps, repeats) * 1000, 2)


def bench_workload_steps() -> dict:
    """BASELINE rows 'MLPMnist / LeNet CIFAR-10 / LSTM UCI-HAR step time'
    (SURVEY §7.2 M1/M3/M4 measurements)."""
    from deeplearning4j_tpu.models import mlp_mnist, lenet, lstm_classifier
    rng = np.random.default_rng(0)
    out = {}
    net = mlp_mnist()
    out["mlp_mnist_step_ms"] = _bench_net_step(
        net, rng.normal(size=(128, 784)).astype(np.float32),
        np.eye(10, dtype=np.float32)[rng.integers(0, 10, 128)])
    net = lenet(height=32, width=32, channels=3)       # CIFAR-10 shape
    out["lenet_cifar10_step_ms"] = _bench_net_step(
        net, rng.normal(size=(128, 32, 32, 3)).astype(np.float32),
        np.eye(10, dtype=np.float32)[rng.integers(0, 10, 128)])
    net = lstm_classifier(timesteps=128)               # UCI-HAR shape
    out["lstm_har_step_ms"] = _bench_net_step(
        net, rng.normal(size=(64, 128, 9)).astype(np.float32),
        np.eye(6, dtype=np.float32)[rng.integers(0, 6, 64)])
    return out


def _cpu_subbench(script_name: str, timeout_s: float) -> dict:
    """Run a bench/ script in a subprocess pinned to CPU and scrape its
    one json line — the pattern that keeps a record measurable even
    when the TPU tunnel is down."""
    import subprocess

    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "bench", script_name)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)   # no virtual-device carryover
    proc = subprocess.run([sys.executable, script], capture_output=True,
                          text=True, timeout=timeout_s, env=env)
    lines = [ln for ln in proc.stdout.splitlines() if ln.startswith("{")]
    if lines:
        return json.loads(lines[-1])
    return {"error": (proc.stderr or "no output")[-300:]}


def bench_feed_overlap(timeout_s: float = 300.0) -> dict:
    """Device-feed pipeline micro-bench (docs/data_pipeline.md):
    DeviceFeeder on vs off steps/sec + recompile counts over an
    ETL-heavy ragged epoch."""
    return _cpu_subbench("feed_overlap.py", timeout_s)


def bench_serving(timeout_s: float = 300.0) -> dict:
    """Inference-serving micro-bench (docs/serving.md): batch-1
    sequential vs dynamic micro-batching — p50/p99 latency, requests/sec
    and compiled-program counts across ragged request shapes."""
    return _cpu_subbench("serving.py", timeout_s)


def bench_online(timeout_s: float = 300.0) -> dict:
    """Closed-loop continual-learning record (docs/online.md):
    feedback→deploy latency, gate eval seconds, and rollback MTTR for
    the tpudl.online loop — spool → fine-tune → eval gate → verified
    hot-swap → watch-triggered rollback.  A CPU subprocess, so the row
    lands even when the TPU tunnel is down."""
    return _cpu_subbench("online.py", timeout_s)


def bench_multichip(timeout_s: float = 900.0) -> dict:
    """Multichip scaling record (ROADMAP item 2's deliverable, CPU
    form): a real spawn_local_cluster gang whose per-worker throughput
    is measured from FEDERATED telemetry (RemoteStatsRouter → the
    coordinator UIServer) — reports measured
    ``per_chip_scaling_efficiency`` and ``straggler_skew``.  A CPU
    subprocess, so the row lands even when the TPU tunnel is down."""
    return _cpu_subbench("multichip.py", timeout_s)


def _tunnel_shaped(message: str) -> bool:
    """Does this failure text mean "the accelerator was unreachable"
    (→ structured skip) rather than "the bench harness is broken"
    (→ rc=1 error)?  Shares the marker list with the trajectory
    sentinel so the writer and the reader agree on what a tunnel-down
    looks like."""
    try:
        from deeplearning4j_tpu.obs.trend import looks_tunnel_down
        return looks_tunnel_down(message)
    except Exception:
        return "tunnel" in (message or "").lower()


def _stamp_trend(record: dict) -> dict:
    """Write-time trajectory verdict: every new bench record carries
    its own stale/ok/regression classification against the committed
    BENCH_r* history (``record["trend"]``).  Best-effort by contract —
    a missing trajectory costs the stamp, never the record."""
    try:
        from deeplearning4j_tpu.obs import trend
        trend.stamp_verdict(record)
    except Exception:
        pass
    return record


def _probe_device(timeout_s: float = 30.0) -> tuple[str, str] | None:
    """Touch the accelerator in a SUBPROCESS with a hard timeout: a down
    TPU tunnel makes backend init HANG (not raise) in some environments
    and silently FALL BACK to CPU in others — either way the TPU bench
    has nothing to measure.  Returns None when a real accelerator
    answers, else ``(status, message)`` where status is ``"skipped"``
    (probe timed out or answered with a CPU — tunnel down; BENCH_r05
    burned 5 minutes at the old 300s timeout to report rc=1, and the
    CPU-fallback mode would grind the full suite for hours to report a
    meaningless vs_baseline) or ``"error"`` (device answered with a
    failure worth a non-zero exit)."""
    import subprocess
    try:
        p = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); "
             "print(d[0].platform, len(d), d[0])"],
            capture_output=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return ("skipped",
                f"device probe timed out after {timeout_s:.0f}s (tunnel down?)")
    if p.returncode != 0:
        stderr = p.stderr.decode()[-400:]
        if _tunnel_shaped(stderr):
            # the probe ANSWERED, but with a tunnel-shaped failure
            # (connection refused / deadline exceeded): same verdict as
            # a hang — nothing TPU-measurable, structured skip, rc=0.
            # BENCH_r05 took this exact situation to an rc=1 with
            # value 0.0 and no status key; the skip contract says a 0.0
            # must never read as a measurement.
            return ("skipped",
                    f"TPU tunnel down at probe (rc={p.returncode}): "
                    f"{stderr[-200:]}")
        return ("error", f"device probe failed (rc={p.returncode}): "
                         f"{stderr[-200:]}")
    answer = p.stdout.decode().strip()
    if answer.startswith("cpu"):
        return ("skipped",
                f"TPU tunnel down: jax fell back to CPU ({answer!r}) — "
                f"nothing TPU-measurable; CPU rows still run")
    return None


def main():
    probe = _probe_device()
    if probe:
        status, err = probe
        # a 0.0 must never read as a measurement: a hung tunnel is a
        # structured "skipped" record with rc=0 (nothing measurable, not
        # a bench failure); a device that answered with an error keeps
        # the nonzero-exit error contract
        detail = {"note": "TPU unreachable at bench time; see BENCH_r04 "
                          "+ bench/PROFILE.md for the last measured "
                          "numbers"}
        try:  # CPU-runnable: the feed pipeline row survives a down tunnel
            detail["feed_overlap"] = bench_feed_overlap()
        except Exception as e:
            detail["feed_overlap"] = {"error": str(e)[:200]}
        try:  # CPU-runnable: the serving row survives a down tunnel too
            detail["serving"] = bench_serving()
        except Exception as e:
            detail["serving"] = {"error": str(e)[:200]}
        try:  # CPU-runnable: the multichip scaling row survives too —
              # a tunnel-down round still measures the gang (rc=0, not
              # the rc=1 the old device-only records produced)
            detail["multichip"] = bench_multichip()
        except Exception as e:
            detail["multichip"] = {"error": str(e)[:200]}
        try:  # CPU-runnable: the continual-learning loop row too
            detail["online"] = bench_online()
        except Exception as e:
            detail["online"] = {"error": str(e)[:200]}
        # a tunnel-down round still reports roofline numbers: lift the
        # cost_analysis-derived stamp out of whichever CPU record
        # produced one (feed_overlap trains a real net under the cost
        # model; serving measures its compiled forward)
        for record in (detail.get("feed_overlap"), detail.get("serving")):
            if isinstance(record, dict) and record.get("mfu") is not None:
                for key in ("mfu", "hbm_util", "arith_intensity"):
                    detail[key] = record.get(key)
                detail["perf"] = record.get("perf")
                break
        print(json.dumps(_stamp_trend(
            {"metric": "resnet50_train_images_per_sec_per_chip",
             "value": 0.0, "unit": "images/sec/chip",
             "vs_baseline": 0.0, "status": status, "error": err,
             "detail": detail})))
        return 0 if status == "skipped" else 1
    batch = 256  # HBM-bound workload: large batch amortizes weight traffic
                 # (see bench/PROFILE.md; 256 ≈ saturation point on v5e)
    for attempt in range(3):
        try:
            result = bench_resnet50(batch=batch)
            try:  # second headline metric: BERT-base MLM step time
                result["detail"]["bert_base_mlm"] = bench_bert_mlm()
            except Exception as e:
                result["detail"]["bert_base_mlm"] = {"error": str(e)[:200]}
            try:  # BASELINE M1/M3/M4 workload step times
                result["detail"]["workloads"] = bench_workload_steps()
            except Exception as e:
                result["detail"]["workloads"] = {"error": str(e)[:200]}
            try:  # long-seq BERT: flash (Pallas fwd+bwd) vs einsum
                result["detail"]["bert_long_seq"] = bench_bert_long_seq()
            except Exception as e:
                result["detail"]["bert_long_seq"] = {"error": str(e)[:200]}
            try:  # multi-slice DCN: wire/overhead row (r5, workload #5)
                result["detail"]["dcn_multislice"] = bench_dcn_multislice()
            except Exception as e:
                result["detail"]["dcn_multislice"] = {"error": str(e)[:200]}
            try:  # DP scaling: CPU-mesh measurement + ICI model (#5)
                result["detail"]["dp_scaling"] = bench_dp_scaling(
                    measured_img_per_sec=result["value"],
                    measured_step_ms=result["detail"]["step_time_ms"])
            except Exception as e:
                result["detail"]["dp_scaling"] = {"error": str(e)[:200]}
            try:  # device-feed pipeline: prefetch overlap + recompile guard
                result["detail"]["feed_overlap"] = bench_feed_overlap()
            except Exception as e:
                result["detail"]["feed_overlap"] = {"error": str(e)[:200]}
            try:  # serving: sequential vs dynamic micro-batching
                result["detail"]["serving"] = bench_serving()
            except Exception as e:
                result["detail"]["serving"] = {"error": str(e)[:200]}
            try:  # multichip: federated-telemetry scaling + straggler skew
                result["detail"]["multichip"] = bench_multichip()
            except Exception as e:
                result["detail"]["multichip"] = {"error": str(e)[:200]}
            try:  # online loop: feedback→deploy, gate eval, rollback MTTR
                result["detail"]["online"] = bench_online()
            except Exception as e:
                result["detail"]["online"] = {"error": str(e)[:200]}
            try:  # per-compiled-program cost breakdown (top-K by FLOPs)
                from deeplearning4j_tpu.obs import costmodel
                result["detail"]["perf_top_programs"] = \
                    costmodel.top_programs(5)
            except Exception:
                pass
            print(json.dumps(_stamp_trend(result)))
            return 0
        except Exception as e:  # OOM etc. → halve the batch and retry
            msg = str(e)
            if "RESOURCE_EXHAUSTED" in msg or "out of memory" in msg.lower():
                batch //= 2
                continue
            # a tunnel that DIES MID-RUN is the same verdict as one
            # that never answered: structured skip, rc=0 (BENCH_r05
            # recorded this very case as rc=1/value 0.0 — the shape
            # trend.py must special-case forever as "legacy")
            status = "skipped" if _tunnel_shaped(msg) else "error"
            print(json.dumps(_stamp_trend(
                {"metric": "resnet50_train_images_per_sec_per_chip",
                 "value": 0.0, "unit": "images/sec/chip",
                 "vs_baseline": 0.0, "status": status,
                 "error": msg[:400], "detail": {}})))
            return 0 if status == "skipped" else 1
    print(json.dumps(_stamp_trend(
        {"metric": "resnet50_train_images_per_sec_per_chip",
         "value": 0.0, "unit": "images/sec/chip",
         "vs_baseline": 0.0, "status": "error",
         "error": "OOM at batch>=64", "detail": {}})))
    return 1


if __name__ == "__main__":
    sys.exit(main())
