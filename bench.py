#!/usr/bin/env python
"""Benchmark harness — prints ONE json line with the headline metric.

Headline (BASELINE.json): ResNet-50 ImageNet-shape training throughput in
images/sec/chip on the real TPU.  The reference publishes no number
(BASELINE.md), so ``vs_baseline`` is computed against the public
MLPerf-era proxy for the A100 comparison point named by the north star
(~2750 img/s bf16 on one A100 — marked as a proxy, not a reference-repo
measurement).

Runs on whatever platform jax selects (the driver runs it on TPU);
bfloat16 compute policy, synthetic data (no network), steady-state steps
timed after compile+warmup.
"""

import json
import sys
import time

import numpy as np


A100_PROXY_IMG_PER_SEC = 2750.0  # public MLPerf-era proxy, see BASELINE.md


def bench_resnet50(batch: int = 256, image: int = 224, steps: int = 12,
                   warmup: int = 2) -> dict:
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.config import DTypePolicy, set_dtype_policy
    from deeplearning4j_tpu.models import resnet50
    from deeplearning4j_tpu.train.trainer import Trainer
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.train import Nesterovs

    set_dtype_policy(DTypePolicy.bf16())
    net = resnet50(height=image, width=image, num_classes=1000,
                   updater=Nesterovs(0.1, 0.9))
    net.init()
    trainer = Trainer(net)

    rng = np.random.default_rng(0)
    x = rng.uniform(0, 1, size=(batch, image, image, 3)).astype(np.float32)
    y = np.eye(1000, dtype=np.float32)[rng.integers(0, 1000, batch)]
    batch_ds = DataSet(jnp.asarray(x), jnp.asarray(y))
    key = jax.random.key(0)

    for _ in range(warmup):  # first call compiles
        float(trainer.fit_batch(batch_ds, key))
    t0 = time.perf_counter()
    loss = None
    for _ in range(steps):
        loss = trainer.fit_batch(batch_ds, key)  # async dispatch, pipelined
    final_loss = float(loss)  # one sync closes the timed region
    dt = time.perf_counter() - t0
    img_per_sec = batch * steps / dt
    n_chips = max(len(jax.devices()), 1)
    return {
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(img_per_sec / n_chips, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(img_per_sec / n_chips / A100_PROXY_IMG_PER_SEC, 4),
        "detail": {
            "batch": batch, "image": image, "steps": steps,
            "step_time_ms": round(1000 * dt / steps, 2),
            "device": str(jax.devices()[0]),
            "baseline_note": "A100 bf16 public proxy (~2750 img/s); reference repo publishes no number",
        },
    }


def bench_bert_mlm(batch: int = 32, seq_len: int = 128, steps: int = 10,
                   warmup: int = 2) -> dict:
    """BERT-base MLM fine-tune step time — the second headline metric
    (BASELINE.json config #4: SameDiff TF-import BERT-base MLM)."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.config import DTypePolicy, set_dtype_policy
    from deeplearning4j_tpu.models.bert import BertConfig, BertForMaskedLM
    from deeplearning4j_tpu.train import Adam

    set_dtype_policy(DTypePolicy.bf16())
    config = BertConfig.base()
    model = BertForMaskedLM(config, seed=0)
    tx = Adam(2e-5).to_optax()
    opt_state = tx.init(model.params)
    step = model.make_train_step(tx)

    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, config.vocab_size, (batch, seq_len)), jnp.int32)
    labels = jnp.asarray(rng.integers(0, config.vocab_size, (batch, seq_len)), jnp.int32)
    weights = jnp.asarray((rng.random((batch, seq_len)) < 0.15), jnp.float32)
    attn = jnp.ones((batch, seq_len), jnp.float32)
    key = jax.random.key(0)

    params, opt = model.params, opt_state
    for _ in range(warmup):
        params, opt, loss = step(params, opt, ids, labels, weights, attn, key)
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt, loss = step(params, opt, ids, labels, weights, attn, key)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    return {"step_time_ms": round(1000 * dt / steps, 2),
            "batch": batch, "seq_len": seq_len}


def bench_bert_long_seq(seq_len: int = 4096, batch: int = 2,
                        steps: int = 5, warmup: int = 2) -> dict:
    """Long-sequence BERT MLM train step: Pallas flash kernel (fwd+bwd)
    vs the materializing einsum path (SURVEY §5.7 long-seq training)."""
    import dataclasses
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.config import DTypePolicy, set_dtype_policy
    from deeplearning4j_tpu.models import bert as bert_mod
    from deeplearning4j_tpu.train import Adam

    set_dtype_policy(DTypePolicy.bf16())
    base = bert_mod.BertConfig(vocab_size=30522, hidden_size=768,
                               num_layers=4, num_heads=12,
                               intermediate_size=3072, max_position=seq_len)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, base.vocab_size, (batch, seq_len)),
                      jnp.int32)
    labels = jnp.asarray(rng.integers(0, base.vocab_size, (batch, seq_len)),
                         jnp.int32)
    weights = jnp.asarray((rng.random((batch, seq_len)) < 0.15), jnp.float32)
    attn = jnp.ones((batch, seq_len), jnp.float32)
    key = jax.random.key(0)

    out = {"seq_len": seq_len, "batch": batch, "num_layers": base.num_layers}
    for name, cfg in (("einsum", base),
                      ("flash", dataclasses.replace(base, use_flash=True))):
        model = bert_mod.BertForMaskedLM(cfg, seed=0)
        tx = Adam(2e-5).to_optax()
        opt = tx.init(model.params)
        step = model.make_train_step(tx)
        params = model.params
        for _ in range(warmup):
            params, opt, loss = step(params, opt, ids, labels, weights,
                                     attn, key)
        jax.block_until_ready(loss)
        t0 = time.perf_counter()
        for _ in range(steps):
            params, opt, loss = step(params, opt, ids, labels, weights,
                                     attn, key)
        jax.block_until_ready(loss)
        out[f"{name}_step_ms"] = round(
            (time.perf_counter() - t0) / steps * 1000, 2)
    out["flash_speedup"] = round(out["einsum_step_ms"]
                                 / out["flash_step_ms"], 2)
    return out


def _bench_net_step(net, features, labels, steps=10, warmup=2):
    """Steady-state fit_batch time for a workload net."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.train.trainer import Trainer
    trainer = Trainer(net)
    batch = DataSet(jnp.asarray(features), jnp.asarray(labels))
    key = jax.random.key(0)
    for _ in range(warmup):
        loss = trainer.fit_batch(batch, key)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = trainer.fit_batch(batch, key)
    float(loss)
    return round((time.perf_counter() - t0) / steps * 1000, 2)


def bench_workload_steps() -> dict:
    """BASELINE rows 'MLPMnist / LeNet CIFAR-10 / LSTM UCI-HAR step time'
    (SURVEY §7.2 M1/M3/M4 measurements)."""
    from deeplearning4j_tpu.models import mlp_mnist, lenet, lstm_classifier
    rng = np.random.default_rng(0)
    out = {}
    net = mlp_mnist()
    out["mlp_mnist_step_ms"] = _bench_net_step(
        net, rng.normal(size=(128, 784)).astype(np.float32),
        np.eye(10, dtype=np.float32)[rng.integers(0, 10, 128)])
    net = lenet(height=32, width=32, channels=3)       # CIFAR-10 shape
    out["lenet_cifar10_step_ms"] = _bench_net_step(
        net, rng.normal(size=(128, 32, 32, 3)).astype(np.float32),
        np.eye(10, dtype=np.float32)[rng.integers(0, 10, 128)])
    net = lstm_classifier(timesteps=128)               # UCI-HAR shape
    out["lstm_har_step_ms"] = _bench_net_step(
        net, rng.normal(size=(64, 128, 9)).astype(np.float32),
        np.eye(6, dtype=np.float32)[rng.integers(0, 6, 64)])
    return out


def main():
    batch = 256  # HBM-bound workload: large batch amortizes weight traffic
                 # (see bench/PROFILE.md; 256 ≈ saturation point on v5e)
    for attempt in range(3):
        try:
            result = bench_resnet50(batch=batch)
            try:  # second headline metric: BERT-base MLM step time
                result["detail"]["bert_base_mlm"] = bench_bert_mlm()
            except Exception as e:
                result["detail"]["bert_base_mlm"] = {"error": str(e)[:200]}
            try:  # BASELINE M1/M3/M4 workload step times
                result["detail"]["workloads"] = bench_workload_steps()
            except Exception as e:
                result["detail"]["workloads"] = {"error": str(e)[:200]}
            try:  # long-seq BERT: flash (Pallas fwd+bwd) vs einsum
                result["detail"]["bert_long_seq"] = bench_bert_long_seq()
            except Exception as e:
                result["detail"]["bert_long_seq"] = {"error": str(e)[:200]}
            print(json.dumps(result))
            return 0
        except Exception as e:  # OOM etc. → halve the batch and retry
            msg = str(e)
            if "RESOURCE_EXHAUSTED" in msg or "out of memory" in msg.lower():
                batch //= 2
                continue
            print(json.dumps({"metric": "resnet50_train_images_per_sec_per_chip",
                              "value": 0.0, "unit": "images/sec/chip",
                              "vs_baseline": 0.0, "error": msg[:400]}))
            return 1
    print(json.dumps({"metric": "resnet50_train_images_per_sec_per_chip",
                      "value": 0.0, "unit": "images/sec/chip",
                      "vs_baseline": 0.0, "error": "OOM at batch>=64"}))
    return 1


if __name__ == "__main__":
    sys.exit(main())
