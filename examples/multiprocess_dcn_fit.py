"""Multi-PROCESS multi-slice training — the production form of the
SharedTrainingMaster replacement (VERDICT r4 next #1c).

Each process is one slice leader: gradients + residual + threshold
encode run fused in that process's jit step (``device_encode``), the
fixed-capacity message crosses to the host, and a ring
``SocketTransport`` exchanges the compressed bytes between processes
while the next step's gradients compute (``overlap``).  Params stay
byte-identical across processes without any parameter broadcast.

Run:  python examples/multiprocess_dcn_fit.py
(spawns 2 local worker processes over loopback; the same worker code
runs unchanged across real hosts by passing ``hosts=`` to
SocketTransport and a real coordinator to ``launcher.initialize``.)
"""

from __future__ import annotations


import os
import sys

import numpy as np


def worker(pid: int, n: int, steps: int = 8, port: int = 23801):
    import jax

    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.nn import NeuralNetConfiguration, InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.compression import (
        AdaptiveThresholdAlgorithm)
    from deeplearning4j_tpu.parallel.dcn import SocketTransport
    from deeplearning4j_tpu.parallel.dcn_trainer import MultiSliceTrainer
    from deeplearning4j_tpu.train import Sgd

    conf = (NeuralNetConfiguration.builder().seed(7).updater(Sgd(0.1))
            .weight_init("xavier").list()
            .layer(DenseLayer(n_out=32, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8)).build())
    net = MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(5)
    x = rng.normal(size=(64, 8)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 64)]
    local = DataSet(x[pid::n], y[pid::n])     # this process's shard

    transport = SocketTransport(pid, n, port=port, timeout=30.0)
    trainer = MultiSliceTrainer(
        net, n_slices=1, world_size=n, rank_offset=pid,
        transports=[transport], device_encode=True, overlap=True,
        devices=jax.local_devices(),
        algorithm=AdaptiveThresholdAlgorithm(initial_threshold=2e-2))
    key = jax.random.key(0)
    losses = []
    try:
        for _ in range(steps):
            key, sub = jax.random.split(key)
            losses.append(trainer.fit_batch(local, sub))
        trainer.collect()
    finally:
        trainer.close()
        transport.close()

    from deeplearning4j_tpu.utils.pytree import flat_param_vector
    ws = trainer.last_wire_stats[0]
    return {"pid": pid, "losses": losses,
            "params": np.asarray(flat_param_vector(net.params_)),
            "wire_bytes": ws["wire_bytes"], "dense_bytes": ws["dense_bytes"],
            "ring_bytes_sent": transport.bytes_sent}


def main():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import multiprocess_dcn_fit as mod   # importable twin of __main__
    from deeplearning4j_tpu.parallel.launcher import spawn_local_cluster

    env = {"PYTHONPATH": os.path.dirname(os.path.abspath(__file__))
           + os.pathsep + os.environ.get("PYTHONPATH", "")}
    results = spawn_local_cluster(mod.worker, n_processes=2, port=12741,
                                  local_devices=1, extra_env=env)
    a, b = sorted(results, key=lambda r: r["pid"])
    drift = float(np.abs(a["params"] - b["params"]).max())
    print(f"losses (rank 0): {[round(l, 4) for l in a['losses']]}")
    print(f"param drift between processes: {drift:.1e} (0.0 = byte-identical)")
    print(f"wire {a['wire_bytes']}B vs dense {a['dense_bytes']}B per step; "
          f"ring sent {a['ring_bytes_sent']}B total")
    assert drift == 0.0


if __name__ == "__main__":
    main()
