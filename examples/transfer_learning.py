"""Transfer learning — pretrain a small conv net, then graft a new output
head, freeze the feature extractor, and fine-tune on a new task
(dl4j-examples ``TransferLearning`` / ``EditLastLayerOthersFrozen``)."""

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator
from deeplearning4j_tpu.models import lenet
from deeplearning4j_tpu.nn.layers import OutputLayer
from deeplearning4j_tpu.nn.transfer import (FineTuneConfiguration,
                                            TransferLearning)
from deeplearning4j_tpu.train import Adam


def _batches(n, classes, seed, batch=32):
    rng = np.random.default_rng(seed)
    xs = rng.normal(size=(n, 28, 28, 1)).astype(np.float32)
    ys = np.eye(classes, dtype=np.float32)[rng.integers(0, classes, n)]
    return ListDataSetIterator(
        [DataSet(xs[i:i + batch], ys[i:i + batch])
         for i in range(0, n, batch)])


def main(pretrain_epochs: int = 1, finetune_epochs: int = 1,
         new_classes: int = 5, verbose: bool = True):
    base = lenet(num_classes=10).init()
    base.fit(_batches(128, 10, seed=0), epochs=pretrain_epochs)

    new_net = (TransferLearning.builder(base)
               .fine_tune_configuration(FineTuneConfiguration(updater=Adam(1e-3)))
               .set_feature_extractor(3)          # freeze everything below
               .remove_output_layer()
               .add_layer(OutputLayer(n_out=new_classes, activation="softmax",
                                      loss="mcxent"))
               .build())
    frozen_before = np.asarray(new_net.params_[0]["W"], dtype=np.float32)
    new_net.fit(_batches(128, new_classes, seed=1), epochs=finetune_epochs)
    frozen_after = np.asarray(new_net.params_[0]["W"], dtype=np.float32)
    if verbose:
        print(f"feature extractor unchanged: "
              f"{np.array_equal(frozen_before, frozen_after)}")
    return new_net


if __name__ == "__main__":
    main()
