"""Training observability — per-layer stats into a storage, a static HTML
report, and the live dashboard server
(dl4j-examples ``UIExample``: ``UIServer.getInstance().attach(storage)``)."""

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.obs import (InMemoryStatsStorage, StatsListener,
                                    UIServer, render_html_report)
from deeplearning4j_tpu.train import Adam


def main(epochs: int = 3, report_path: str = "/tmp/training_report.html",
         serve: bool = False, verbose: bool = True):
    conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(5e-3)).list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8)).build())
    net = MultiLayerNetwork(conf).init()

    storage = InMemoryStatsStorage()
    server = None
    if serve:
        server = UIServer.get_instance()
        server.attach(storage)
        if verbose:
            print("dashboard at", server.url)

    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 8)).astype(np.float32)
    w = rng.normal(size=(8, 3)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[np.argmax(x @ w, -1)]
    it = ListDataSetIterator([DataSet(x[i:i + 32], y[i:i + 32])
                              for i in range(0, 256, 32)])
    net.fit(it, epochs=epochs, listeners=[StatsListener(storage, frequency=2)])

    out = render_html_report(storage, report_path)
    if verbose:
        print("report written to", out)
    return out


if __name__ == "__main__":
    main()
