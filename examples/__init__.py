"""Runnable examples — parity with the reference's ``dl4j-examples``
gallery: each script is a small end-to-end workflow on the public API,
with fast synthetic-data defaults so they run anywhere (pass bigger
sizes / real data roots for real runs).  Smoke-tested in
``tests/test_examples.py``."""
