"""MLP on MNIST — the canonical first example
(dl4j-examples ``MLPMnistSingleLayerExample``).

Run with ``DL4J_TPU_TRACING=1`` to get a Chrome-trace JSON of the
``fit`` → ``epoch`` → ``step`` spans under ``config.trace_dir``
(open it in chrome://tracing or https://ui.perfetto.dev)."""

import os

from deeplearning4j_tpu.config import get_config
from deeplearning4j_tpu.data import datasets
from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.obs import ScoreIterationListener, get_tracer
from deeplearning4j_tpu.train import Adam


def main(epochs: int = 2, batch_size: int = 128, hidden: int = 256,
         n_synthetic: int = 6000, verbose: bool = True):
    conf = (NeuralNetConfiguration.builder()
            .seed(42)
            .updater(Adam(1e-3))
            .list()
            .layer(DenseLayer(n_out=hidden, activation="relu"))
            .layer(OutputLayer(n_out=10, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(784))
            .build())
    net = MultiLayerNetwork(conf).init()

    train = datasets.mnist(batch_size=batch_size, train=True,
                           n_synthetic=n_synthetic)
    test = datasets.mnist(batch_size=256, train=False,
                          n_synthetic=n_synthetic)
    listeners = [ScoreIterationListener(10)] if verbose else None
    net.fit(train, epochs=epochs, listeners=listeners)

    cfg = get_config()
    if cfg.tracing:
        path = os.path.join(cfg.trace_dir, "mlp_mnist_trace.json")
        get_tracer().export_chrome_trace(path)
        get_tracer().export_jsonl(os.path.join(cfg.trace_dir,
                                               "mlp_mnist_spans.jsonl"))
        if verbose:
            print(f"chrome trace: {path}")

    ev = net.evaluate(test)
    if verbose:
        print(ev.stats())
    return ev.accuracy()


if __name__ == "__main__":
    main()
