"""Traffic-scale serving: load ramp → autoscale → fan-out swap → rollback.

Demonstrates the scale-out serving plane end to end
(docs/serving.md "Scale-out"):

1. deploy one model into a :class:`ModelRegistry` (verified load) and
   attach a :class:`ReplicaRouter` with priority lanes, a per-tenant
   token-bucket quota, and a queue-depth :class:`Autoscaler`;
2. ramp closed-loop client load — the autoscaler grows the replica set
   (scale-up is milliseconds: every replica shares the step-cached
   compiled forward);
3. fan-out hot-swap to v2 while the clients keep hammering — every
   replica flips atomically, old engines drain, zero dropped or
   garbled responses, ``ready()`` stays true throughout;
4. force an all-replica rollback: ``registry.rollback`` delegates to
   the router, so the WHOLE fleet returns to v1's weights together.

Run: ``python -m examples.replica_scaling``
"""

import os
import tempfile
import threading

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.serve import (AdmissionControl, AutoscaleConfig,
                                      Autoscaler, Lane, ModelRegistry,
                                      Overloaded, ReplicaRouter,
                                      TenantQuota)
from deeplearning4j_tpu.train import Adam

N_IN, N_CLASSES, HIDDEN, DEPTH = 64, 8, 512, 4


def _net(x, y, epochs):
    builder = (NeuralNetConfiguration.builder().seed(5).updater(Adam(1e-2))
               .list())
    for _ in range(DEPTH):
        builder = builder.layer(DenseLayer(n_out=HIDDEN, activation="relu"))
    conf = (builder
            .layer(OutputLayer(n_out=N_CLASSES, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(N_IN)).build())
    net = MultiLayerNetwork(conf).init()
    if epochs:
        batches = [DataSet(x[i:i + 16], y[i:i + 16])
                   for i in range(0, len(x), 16)]
        net.fit(ListDataSetIterator(batches), epochs=epochs)
    return net


def main(workdir=None, clients=12, reqs_per_client=30, verbose=True):
    workdir = workdir or tempfile.mkdtemp(prefix="tpudl_replicas_")
    rng = np.random.default_rng(7)
    x = rng.normal(size=(64, N_IN)).astype(np.float32)
    w = rng.normal(size=(N_IN, N_CLASSES)).astype(np.float32)
    y = np.eye(N_CLASSES, dtype=np.float32)[np.argmax(x @ w, -1)]

    p1 = os.path.join(workdir, "model_v1.zip")
    p2 = os.path.join(workdir, "model_v2.zip")
    net1 = _net(x, y, epochs=0)
    net1.save(p1)
    net2 = _net(x, y, epochs=1)        # same architecture, moved weights
    net2.save(p2)
    exp = {0: np.asarray(net1.output(x)), 1: np.asarray(net2.output(x))}

    registry = ModelRegistry(max_batch=8, max_latency_ms=2.0,
                             queue_limit=8)
    registry.deploy("classifier", p1)                     # verified load
    router = ReplicaRouter(
        registry, "classifier", replicas=1, max_replicas=4,
        admission=AdmissionControl(
            lanes=[Lane("interactive", 0, shed_at=1.0),
                   Lane("batch", 1, shed_at=0.5)],
            quotas={"free-tier": TenantQuota(rate=200, burst=400)}))
    scaler = Autoscaler(router, AutoscaleConfig(
        scale_up_at=0.1, scale_down_at=0.01, poll_s=0.01,
        up_cooldown_s=0.02, down_cooldown_s=60.0))

    results, errors, sheds = [], [], [0]
    lock = threading.Lock()

    def client(cid, swap_evt):
        crng = np.random.default_rng(100 + cid)
        lane = "batch" if cid % 4 == 3 else "interactive"
        for r in range(reqs_per_client):
            i = int(crng.integers(0, x.shape[0] - 2))
            try:
                out = registry.predict(
                    "classifier", x[i:i + 2], timeout_s=60,
                    tenant="free-tier", lane=lane)
            except Overloaded:       # admission shed — not a drop
                with lock:
                    sheds[0] += 1
                continue
            except BaseException as e:    # noqa: BLE001 — must stay empty
                with lock:
                    errors.append(repr(e))
                continue
            with lock:
                results.append((i, np.asarray(out)))
            if r == reqs_per_client // 2:
                swap_evt.set()       # mid-ramp: the deploy plane acts

    try:
        # phase 1+2: load ramp under the autoscaler, with the fan-out
        # hot-swap landing mid-ramp from the main thread
        swap_evt = threading.Event()
        threads = [threading.Thread(target=client, args=(c, swap_evt))
                   for c in range(clients)]
        for t in threads:
            t.start()
        swap_evt.wait(timeout=60)
        entry = router.deploy(p2)              # atomic fan-out, v2
        for t in threads:
            t.join(timeout=120)
        replicas_grown_to = router.replicas
        versions = [1, entry.version]
        if verbose:
            print(f"ramp: {len(results)} answered, {sheds[0]} shed, "
                  f"replicas grew 1 -> {replicas_grown_to}")
            print(f"fan-out swap -> v{entry.version} across "
                  f"{router.replicas} replicas "
                  f"{[r['version'] for r in router.replica_stats()]}")

        # phase 3: forced all-replica rollback (the DeployWatch path —
        # registry.rollback delegates to the router)
        rolled = registry.rollback("classifier")
        versions.append(rolled.version)
        out, version = registry.predict_versioned("classifier", x[:2],
                                                  timeout_s=60)
        assert version == rolled.version
        assert np.allclose(out, exp[0][:2], rtol=1e-4, atol=1e-4)
        if verbose:
            print(f"rollback -> v{rolled.version} (v1 weights) across "
                  f"{[r['version'] for r in router.replica_stats()]}")
    finally:
        scaler.close()
        registry.close()

    garbled = sum(
        1 for i, rows in results
        if not any(np.allclose(rows, exp[v][i:i + 2], rtol=1e-4, atol=1e-4)
                   for v in exp))
    if verbose:
        print(f"dropped={len(errors)} garbled={garbled} "
              f"versions={versions}")
    return {"replicas_grown_to": replicas_grown_to,
            "versions": versions,
            "answered": len(results),
            "shed": sheds[0],
            "dropped": len(errors),
            "garbled": garbled,
            "rolled_back": versions[-1] == 3}


if __name__ == "__main__":
    main()
