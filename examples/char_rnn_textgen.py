"""Character-level text generation with a GravesLSTM + tBPTT
(dl4j-examples ``CharacterIterator`` / ``LSTMCharModellingExample``):
train on a corpus, then sample with ``rnn_time_step`` streaming state."""

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator
from deeplearning4j_tpu.models import text_gen_lstm

DEFAULT_CORPUS = (
    "the quick brown fox jumps over the lazy dog. "
    "pack my box with five dozen liquor jugs. "
    "how vexingly quick daft zebras jump. "
) * 20


def _char_batches(text: str, seq_len: int, batch_size: int):
    chars = sorted(set(text))
    idx = {c: i for i, c in enumerate(chars)}
    ids = np.array([idx[c] for c in text], np.int64)
    v = len(chars)
    n_seq = (len(ids) - 1) // seq_len
    xs = np.zeros((n_seq, seq_len, v), np.float32)
    ys = np.zeros((n_seq, seq_len, v), np.float32)
    for s in range(n_seq):
        seg = ids[s * seq_len:(s + 1) * seq_len + 1]
        xs[s, np.arange(seq_len), seg[:-1]] = 1.0
        ys[s, np.arange(seq_len), seg[1:]] = 1.0
    batches = [DataSet(xs[i:i + batch_size], ys[i:i + batch_size])
               for i in range(0, n_seq, batch_size)]
    return ListDataSetIterator(batches), chars


def sample(net, chars, prime: str = "the ", length: int = 80,
           temperature: float = 0.8, seed: int = 0) -> str:
    rng = np.random.default_rng(seed)
    idx = {c: i for i, c in enumerate(chars)}
    v = len(chars)
    net.rnn_clear_previous_state()
    out = list(prime)
    x = np.zeros((1, len(prime), v), np.float32)
    x[0, np.arange(len(prime)), [idx[c] for c in prime]] = 1.0
    probs = np.asarray(net.rnn_time_step(x))[0, -1]
    for _ in range(length):
        logits = np.log(np.maximum(probs, 1e-9)) / temperature
        p = np.exp(logits - logits.max())
        p /= p.sum()
        c = rng.choice(v, p=p)
        out.append(chars[c])
        step = np.zeros((1, v), np.float32)
        step[0, c] = 1.0
        probs = np.asarray(net.rnn_time_step(step))[0]
    return "".join(out)


def main(epochs: int = 3, seq_len: int = 32, batch_size: int = 16,
         hidden: int = 64, corpus: str = DEFAULT_CORPUS, verbose: bool = True):
    it, chars = _char_batches(corpus, seq_len, batch_size)
    net = text_gen_lstm(vocab_size=len(chars), hidden=hidden,
                        timesteps=seq_len, layers=1).init()
    net.fit(it, epochs=epochs)
    text = sample(net, chars, length=60)
    if verbose:
        print(repr(text))
    return text


if __name__ == "__main__":
    main()
