"""Fault-tolerant training: checkpoint, crash, resume — exactly.

Demonstrates the resilience layer end-to-end (docs/fault_tolerance.md):

1. train with a durable ``CheckpointListener`` (atomic manifested zips,
   per-iteration cadence, keep-last-K);
2. die mid-run from an injected preemption (``FaultPlan`` — the same
   plan an operator would set via ``DL4J_TPU_FAULT_PLAN`` around an
   unmodified script);
3. restart "in a new process": a fresh net + fresh iterator resumed via
   ``Trainer.fit(..., resume_from=dir)`` — RNG key, updater state and
   mid-epoch iterator position all restore, so the per-step losses
   continue the interrupted trajectory to 1e-6 (dropout included).

Run: ``python -m examples.fault_tolerant_training``
"""

import tempfile

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import (
    ListDataSetIterator, ResumableIterator)
from deeplearning4j_tpu.io.checkpoint import CheckpointListener
from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, DropoutLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.obs.listeners import CollectScoresListener
from deeplearning4j_tpu.resilience import InjectedCrash, faults
from deeplearning4j_tpu.train import Adam
from deeplearning4j_tpu.train.trainer import Trainer


def _net(seed=7):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=24, activation="tanh"))
            .layer(DropoutLayer(dropout=0.8))
            .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8)).build())
    return MultiLayerNetwork(conf).init()


def _iterator(n=128, batch=16, seed=5):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 8)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, n)]
    return ResumableIterator(ListDataSetIterator(
        [DataSet(x[i:i + batch], y[i:i + batch]) for i in range(0, n, batch)]))


def main(epochs=2, crash_at_step=11, checkpoint_dir=None, verbose=True):
    checkpoint_dir = checkpoint_dir or tempfile.mkdtemp(prefix="tpudl_ckpt_")

    # ---- reference: the run that never dies --------------------------
    reference = CollectScoresListener()
    Trainer(_net(), listeners=[reference]).fit(_iterator(), epochs=epochs)

    # ---- run 1: preempted mid-epoch ----------------------------------
    survived = CollectScoresListener()
    ckpt = CheckpointListener(checkpoint_dir, save_every_n_iterations=1,
                              keep_last=3)
    try:
        with faults.inject(f"trainer.step@{crash_at_step}:crash"):
            Trainer(_net(), listeners=[survived, ckpt]).fit(
                _iterator(), epochs=epochs)
        raise AssertionError("the injected preemption never fired")
    except InjectedCrash as crash:
        if verbose:
            print(f"preempted: {crash} "
                  f"({len(survived.scores)} steps committed)")

    # ---- run 2: a fresh process resumes ------------------------------
    resumed = CollectScoresListener()
    Trainer(_net(), listeners=[resumed]).fit(
        _iterator(), epochs=epochs, resume_from=checkpoint_dir)

    stitched = survived.scores + resumed.scores
    drift = float(np.abs(np.asarray(stitched)
                         - np.asarray(reference.scores)).max())
    if verbose:
        print(f"resumed {len(resumed.scores)} steps from "
              f"{CheckpointListener.last_checkpoint_in(checkpoint_dir)}")
        print(f"max per-step loss drift vs uninterrupted run: {drift:.2e}")
    return drift


if __name__ == "__main__":
    main()
