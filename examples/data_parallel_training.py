"""Data-parallel training over a device mesh
(dl4j-examples ``ParallelWrapper`` / Spark gradient-sharing examples —
here the allreduce is a dense psum over the mesh's ``data`` axis).

Runs on whatever devices jax sees: 1 TPU chip (mesh of 1), or the
8-virtual-device CPU mesh used in tests
(``XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu``).
"""

import jax
import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import ParallelWrapper, make_mesh
from deeplearning4j_tpu.train import Adam


def main(epochs: int = 2, global_batch: int = 64, verbose: bool = True):
    n_dev = len(jax.devices())
    mesh = make_mesh(data=n_dev)

    conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(5e-3)).list()
            .layer(DenseLayer(n_out=32, activation="relu"))
            .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(12)).build())
    net = MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(0)
    x = rng.normal(size=(512, 12)).astype(np.float32)
    w = rng.normal(size=(12, 4)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[np.argmax(x @ w, -1)]
    it = ListDataSetIterator(
        [DataSet(x[i:i + global_batch], y[i:i + global_batch])
         for i in range(0, 512, global_batch)])

    trainer = ParallelWrapper(net, mesh=mesh)
    trainer.fit(it, epochs=epochs)
    acc = net.evaluate(it).accuracy()
    if verbose:
        print(f"dp={n_dev} devices, accuracy {acc:.3f}")
    return acc


if __name__ == "__main__":
    main()
