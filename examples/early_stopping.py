"""Early stopping — stop on validation-score plateau and restore the best
model (dl4j-examples ``EarlyStoppingMNIST``)."""

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.train import Adam
from deeplearning4j_tpu.train.early_stopping import (
    DataSetLossCalculator, EarlyStoppingConfiguration, EarlyStoppingTrainer,
    MaxEpochsTerminationCondition, ScoreImprovementEpochTerminationCondition)


def _iter(n, seed, batch=32):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 10)).astype(np.float32)
    w = rng.normal(size=(10, 3)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[np.argmax(x @ w, -1)]
    return ListDataSetIterator([DataSet(x[i:i + batch], y[i:i + batch])
                                for i in range(0, n, batch)])


def main(max_epochs: int = 20, patience: int = 3, verbose: bool = True):
    conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(5e-3)).list()
            .layer(DenseLayer(n_out=32, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(10)).build())
    net = MultiLayerNetwork(conf).init()

    es_conf = EarlyStoppingConfiguration(
        score_calculator=DataSetLossCalculator(_iter(96, seed=1)),
        epoch_termination_conditions=[
            MaxEpochsTerminationCondition(max_epochs),
            ScoreImprovementEpochTerminationCondition(patience)],
    )
    result = EarlyStoppingTrainer(es_conf, net, _iter(256, seed=0)).fit()
    if verbose:
        print(f"stopped at epoch {result.total_epochs} "
              f"(best epoch {result.best_model_epoch}, "
              f"best score {result.best_model_score:.4f}): "
              f"{result.termination_reason}")
    return result


if __name__ == "__main__":
    main()
