"""Word2Vec embeddings — train skip-gram vectors and query neighbors
(dl4j-examples ``Word2VecRawTextExample``)."""

import numpy as np

from deeplearning4j_tpu.nlp import Word2Vec


def _corpus(n=120, seed=0):
    rng = np.random.default_rng(seed)
    animals = ["cat", "dog", "horse", "sheep", "cow"]
    tech = ["cpu", "gpu", "tpu", "ram", "disk"]
    return [" ".join(rng.choice(animals if i % 2 == 0 else tech, 6))
            for i in range(n)]


def main(epochs: int = 10, vector_size: int = 32, verbose: bool = True,
         corpus=None):
    model = Word2Vec(vector_size=vector_size, window=3, negative=5,
                     epochs=epochs, sample=0.0, seed=1)
    model.fit(corpus or _corpus())
    if verbose:
        print("nearest(cat):", model.words_nearest("cat", 4))
        print("sim(cat,dog) =", round(model.similarity("cat", "dog"), 3),
              " sim(cat,gpu) =", round(model.similarity("cat", "gpu"), 3))
    return model


if __name__ == "__main__":
    main()
