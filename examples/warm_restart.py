"""Warm restarts: deploy → kill the server → restart → serve in
milliseconds from the compiled-artifact store.

Demonstrates ISSUE 12 (docs/fault_tolerance.md "Warm restarts",
docs/serving.md "Warm restarts"):

1. train a model, save it through the durable serializer, and **bake**
   its compiled serve program into the zip
   (``artifact_store.ensure_zip_artifacts`` — what
   ``ModelRegistry.deploy(bake_artifacts=True)`` and the online gate's
   pre-flip hook do);
2. "run a server and kill it": a subprocess deploys the zip and answers
   one request — first COLD (a copy of the zip with the artifacts
   stripped: the first request pays live XLA compilation), then WARM
   (the baked zip: the restarted process deserializes the executable
   and serves with **zero JIT on the request path**);
3. print the restart → first-response latency before/after.

A restart must be a real process event — an in-process "restart" would
be answered from warm jit caches and lie — so each measurement runs in
a fresh interpreter.

Run: ``python -m examples.warm_restart``
"""

import json
import os
import subprocess
import sys
import tempfile
import zipfile

import numpy as np

N_IN, N_CLASSES = 24, 4
BUCKET = 8

# one restarted server: deploy the zip, answer one request, report
# timings and the zero-JIT evidence
_SERVE_ONCE = r"""
import json, os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["DL4J_TPU_COSTMODEL"] = "0"
import numpy as np
from deeplearning4j_tpu.serve import ModelRegistry
zip_path, n_in, bucket = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
x = np.zeros((bucket, n_in), np.float32)
t0 = time.perf_counter()
registry = ModelRegistry(max_batch=bucket, buckets=(bucket,))
entry = registry.deploy("m", zip_path)
ready_s = time.perf_counter() - t0
out = np.asarray(registry.predict("m", x, timeout_s=300))
total_s = time.perf_counter() - t0
print(json.dumps({"ready_s": round(ready_s, 4),
                  "first_response_s": round(total_s - ready_s, 4),
                  "total_s": round(total_s, 4),
                  "compiled_programs": entry.engine.compiled_programs,
                  "warm_programs": entry.engine.warm_programs,
                  "classes": int(out.shape[-1])}))
registry.close()
"""


def _trained_net(seed=7):
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.iterators import ListDataSetIterator
    from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.train import Adam
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=48, activation="relu"))
            .layer(DenseLayer(n_out=48, activation="relu"))
            .layer(OutputLayer(n_out=N_CLASSES, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(N_IN)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(128, N_IN)).astype(np.float32)
    y = np.eye(N_CLASSES, dtype=np.float32)[rng.integers(0, N_CLASSES, 128)]
    batches = [DataSet(x[i:i + 16], y[i:i + 16]) for i in range(0, 128, 16)]
    net.fit(ListDataSetIterator(batches), epochs=1)
    return net


def _strip_artifacts(src, dst):
    """A copy of the zip WITHOUT its artifact store (the pre-ISSUE-12
    deployable) — written through the durable writer so the manifest
    stays consistent."""
    from deeplearning4j_tpu.resilience.checkpoint import (
        MANIFEST_NAME, write_checkpoint_zip)
    entries = {}
    with zipfile.ZipFile(src) as zf:
        for name in zf.namelist():
            if name != MANIFEST_NAME and not name.startswith("artifacts/"):
                entries[name] = zf.read(name)
    write_checkpoint_zip(dst, entries)


def _serve_once(zip_path):
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "DL4J_TPU_COSTMODEL": "0",
           "PYTHONPATH": repo_root + os.pathsep
           + os.environ.get("PYTHONPATH", "")}
    proc = subprocess.run(
        [sys.executable, "-c", _SERVE_ONCE, zip_path, str(N_IN),
         str(BUCKET)],
        capture_output=True, text=True, timeout=600, env=env)
    if proc.returncode != 0:
        raise RuntimeError(f"server process failed rc={proc.returncode}:\n"
                           f"{proc.stderr[-2000:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def main(workdir=None, verbose=True):
    from deeplearning4j_tpu.train import artifact_store

    def say(*args):
        if verbose:
            print(*args)

    workdir = workdir or tempfile.mkdtemp(prefix="tpudl_warm_restart_")
    warm_zip = os.path.join(workdir, "model.zip")
    cold_zip = os.path.join(workdir, "model_noartifacts.zip")

    say("== train + deploy-time bake")
    net = _trained_net()
    net.save(warm_zip)
    baked = artifact_store.ensure_zip_artifacts(warm_zip, net=net,
                                                buckets=(BUCKET,))
    say(f"   baked {baked} serve program(s) into "
        f"{os.path.basename(warm_zip)}")
    _strip_artifacts(warm_zip, cold_zip)

    say("== kill the server, restart COLD (no artifact store)")
    cold = _serve_once(cold_zip)
    say(f"   restart -> first response: {cold['total_s'] * 1e3:.0f} ms "
        f"(first request waited {cold['first_response_s'] * 1e3:.0f} ms "
        f"on live XLA compile; {cold['compiled_programs']} program "
        f"traced)")

    say("== kill the server, restart WARM (artifact store in the zip)")
    warm = _serve_once(warm_zip)
    say(f"   restart -> first response: {warm['total_s'] * 1e3:.0f} ms "
        f"(first request waited {warm['first_response_s'] * 1e3:.0f} ms; "
        f"{warm['compiled_programs']} programs traced, "
        f"{warm['warm_programs']} served from the store)")

    result = {
        "cold": cold, "warm": warm,
        "restart_speedup": round(cold["total_s"]
                                 / max(warm["total_s"], 1e-9), 2),
        "first_response_speedup": round(
            cold["first_response_s"]
            / max(warm["first_response_s"], 1e-9), 2),
        "zero_jit_after_warm": warm["compiled_programs"] == 0
        and warm["warm_programs"] >= 1,
    }
    say(f"== warm restart {result['restart_speedup']}x faster end to end, "
        f"first response {result['first_response_speedup']}x faster, "
        f"zero JIT on the request path: {result['zero_jit_after_warm']}")
    return result


if __name__ == "__main__":
    main()
