"""BERT MLM fine-tune — tokenize a corpus, build MLM batches, fine-tune
(BASELINE workload #4; reference: ``BertIterator`` + samediff TF import)."""

from deeplearning4j_tpu.models.bert import BertConfig, BertForMaskedLM
from deeplearning4j_tpu.nlp import (BertIterator, BertWordPieceTokenizer,
                                    CollectionSentenceProvider, build_vocab)
from deeplearning4j_tpu.train import Adam

CORPUS = [
    "the model predicts masked words from context",
    "attention layers mix information across positions",
    "training minimizes the masked language loss",
    "tokenizers split words into subword pieces",
] * 8


def main(epochs: int = 2, seq_len: int = 16, batch_size: int = 8,
         corpus=None, verbose: bool = True):
    corpus = corpus or CORPUS
    vocab = build_vocab(corpus, max_size=512)
    tok = BertWordPieceTokenizer(vocab)
    it = BertIterator(tok, CollectionSentenceProvider(corpus),
                      seq_len=seq_len, batch_size=batch_size, seed=7)

    config = BertConfig(vocab_size=len(vocab), hidden_size=64, num_layers=2,
                        num_heads=2, intermediate_size=128,
                        max_position=seq_len)
    model = BertForMaskedLM(config, seed=0)
    from deeplearning4j_tpu.obs import CollectScoresListener
    scores = CollectScoresListener()
    model.fit(it, updater=Adam(5e-4), epochs=epochs, listeners=[scores])
    losses = scores.scores
    if verbose:
        print(f"first loss {losses[0]:.3f} -> last {losses[-1]:.3f}")
    return losses


if __name__ == "__main__":
    main()
