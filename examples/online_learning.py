"""Closed-loop continual learning: serve → feedback → fine-tune → gated
hot-swap → forced rollback.

Demonstrates the ``tpudl.online`` subsystem end to end (docs/online.md):

1. train a deliberately-weak v1 classifier, deploy it, and stand up the
   HTTP :class:`ModelServer` with a :class:`FeedbackLog` spool attached;
2. serve live traffic: ``POST :predict`` requests flow through the
   micro-batcher, labeled requests are tapped into the spool, and
   ``POST /v1/models/<name>:feedback`` delivers explicit ground truth;
3. a background :class:`OnlineTrainer` picks the feedback up, fine-tunes
   from the latest verified checkpoint with a
   :class:`~deeplearning4j_tpu.obs.health.HealthMonitor` attached,
   eval-gates the candidate against the incumbent on a held-out slice,
   and hot-swaps it through the registry's verified path — the serving
   version flips with zero dropped requests;
4. a post-deploy :class:`DeployWatch` window watches the live
   ``tpudl_serve_*`` series; a forced error burst triggers the
   automatic rollback to the previous version.

Run: ``python -m examples.online_learning``
"""

import http.client
import json
import os
import tempfile
import threading
import time

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.obs.registry import get_registry
from deeplearning4j_tpu.online import (DeployWatch, EvalGate, OnlineConfig,
                                       OnlineTrainer)
from deeplearning4j_tpu.serve import FeedbackLog, ModelRegistry, ModelServer
from deeplearning4j_tpu.train import Adam

N_IN, N_CLASSES = 12, 3


def _post(port, path, body):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("POST", path, body=json.dumps(body))
    response = conn.getresponse()
    out = json.loads(response.read().decode())
    conn.close()
    return response.status, out


def main(feedback_records=64, verbose=True, workdir=None,
         deploy_timeout_s=60.0):
    workdir = workdir or tempfile.mkdtemp(prefix="tpudl_online_")
    rng = np.random.default_rng(7)
    w = rng.normal(size=(N_IN, N_CLASSES)).astype(np.float32)

    def make_xy(n, seed):
        r = np.random.default_rng(seed)
        x = r.normal(size=(n, N_IN)).astype(np.float32)
        return x, np.eye(N_CLASSES, dtype=np.float32)[np.argmax(x @ w, -1)]

    # 1. a weak v1 (one pass over a little data), deployed + served
    conf = (NeuralNetConfiguration.builder().seed(5).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=24, activation="relu"))
            .layer(OutputLayer(n_out=N_CLASSES, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(N_IN)).build())
    net = MultiLayerNetwork(conf).init()
    x0, y0 = make_xy(32, 1)
    net.fit(ListDataSetIterator([DataSet(x0, y0)]), epochs=1)
    base = os.path.join(workdir, "base.zip")
    net.save(base)

    registry = ModelRegistry(max_batch=8, max_latency_ms=2.0)
    registry.deploy("clf", base)
    feedback = FeedbackLog(os.path.join(workdir, "spool"))
    server = ModelServer(registry, feedback=feedback)

    hx, hy = make_xy(128, 3)
    gate = EvalGate(ListDataSetIterator([DataSet(hx, hy)]),
                    metric="accuracy", min_delta=0.02)
    trainer = OnlineTrainer(
        registry, "clf", feedback.directory,
        os.path.join(workdir, "online"), gate, base,
        config=OnlineConfig(min_records=feedback_records, batch_size=16,
                            max_records_per_round=feedback_records,
                            epochs_per_round=2, interval_s=0.0,
                            poll_s=0.1))
    result = {"workdir": workdir, "versions": []}
    try:
        # 2. live traffic: plain predicts + a labeled predict (tapped
        # into the spool) + explicit :feedback posts
        xq, yq = make_xy(feedback_records, 2)
        status, body = _post(server.port, "/v1/models/clf:predict",
                             {"instances": xq[:4].tolist()})
        assert status == 200, body
        result["versions"].append(body["model_version"])
        status, body = _post(server.port, "/v1/models/clf:predict",
                             {"instances": xq[:8].tolist(),
                              "labels": yq[:8].tolist()})
        assert status == 200, body
        status, body = _post(server.port, "/v1/models/clf:feedback",
                             {"instances": xq[8:].tolist(),
                              "labels": yq[8:].tolist()})
        assert status == 200 and body["accepted"] == feedback_records - 8, \
            body
        if verbose:
            print(f"spooled {feedback_records} feedback records "
                  f"(8 via the labeled-predict tap)")

        # 3. the background loop notices, fine-tunes, gates, hot-swaps
        trainer.start()
        deadline = time.monotonic() + deploy_timeout_s
        while time.monotonic() < deadline \
                and registry.get("clf").version < 2:
            time.sleep(0.1)
        trainer.stop()
        version = registry.get("clf").version
        assert version >= 2, "gated deploy did not happen in time"
        status, body = _post(server.port, "/v1/models/clf:predict",
                             {"instances": xq[:4].tolist()})
        assert status == 200, body
        result["versions"].append(body["model_version"])
        result["deploys"] = int(get_registry().counter(
            "tpudl_online_deploys_total").value)
        if verbose:
            print(f"gated hot-swap: serving v{version} "
                  f"(gate deploys so far: {result['deploys']})")

        # 4. forced rollback: an error burst inside the watch window
        requests = get_registry().labeled_counter(
            "tpudl_serve_requests_total")
        watch = DeployWatch(registry, "clf", window_s=15.0, poll_s=0.05,
                            error_rate_max=0.25, min_requests=4)

        def burst():
            time.sleep(0.1)
            requests.inc(16, status="error")
            requests.inc(4, status="ok")

        threading.Thread(target=burst, daemon=True).start()
        verdict = watch.run()
        assert verdict["rolled_back"], verdict
        result["rolled_back"] = True
        result["rollback_mttr_s"] = verdict["mttr_s"]
        status, body = _post(server.port, "/v1/models/clf:predict",
                             {"instances": xq[:4].tolist()})
        assert status == 200, body
        result["versions"].append(body["model_version"])
        if verbose:
            print(f"rollback after injected regression: serving "
                  f"v{body['model_version']} "
                  f"(mttr {verdict['mttr_s'] * 1e3:.1f} ms)")
            print(f"versions served: {result['versions']}")
    finally:
        trainer.stop()
        server.stop()
        registry.close()
        feedback.close()
    return result


if __name__ == "__main__":
    main()
