"""Production model serving: deploy, predict over HTTP, hot-swap, roll back.

Demonstrates the serve subsystem end-to-end (docs/serving.md):

1. train two versions of a classifier and save them through the durable
   serializer (atomic, sha256-manifested zips — the only door into the
   registry);
2. deploy v1 into a :class:`ModelRegistry` and stand up the JSON
   :class:`ModelServer`; predictions flow through the
   :class:`InferenceEngine`'s dynamic micro-batcher;
3. hot-swap to v2 while the server is up — in-flight requests finish on
   v1, new requests route to v2, ``/healthz`` flips to 503 only for the
   swap window;
4. roll back: v1's zip is re-verified and redeployed as version 3.

Run: ``python -m examples.model_serving``
"""

import http.client
import json
import os
import tempfile

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.serve import ModelRegistry, ModelServer
from deeplearning4j_tpu.train import Adam

N_IN, N_CLASSES = 16, 4


def _trained_net(seed, x, y, epochs):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=32, activation="relu"))
            .layer(OutputLayer(n_out=N_CLASSES, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(N_IN)).build())
    net = MultiLayerNetwork(conf).init()
    batches = [DataSet(x[i:i + 16], y[i:i + 16]) for i in range(0, len(x), 16)]
    net.fit(ListDataSetIterator(batches), epochs=epochs)
    return net


def _post_predict(port, name, instances):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("POST", f"/v1/models/{name}:predict",
                 body=json.dumps({"instances": instances}))
    response = conn.getresponse()
    body = json.loads(response.read().decode())
    conn.close()
    return response.status, body


def main(train_epochs=2, workdir=None, verbose=True):
    workdir = workdir or tempfile.mkdtemp(prefix="tpudl_serving_")
    rng = np.random.default_rng(3)
    x = rng.normal(size=(64, N_IN)).astype(np.float32)
    w = rng.normal(size=(N_IN, N_CLASSES)).astype(np.float32)
    y = np.eye(N_CLASSES, dtype=np.float32)[np.argmax(x @ w, -1)]

    v1_path = os.path.join(workdir, "model_v1.zip")
    v2_path = os.path.join(workdir, "model_v2.zip")
    _trained_net(1, x, y, train_epochs).save(v1_path)
    _trained_net(2, x, y, 2 * train_epochs).save(v2_path)

    registry = ModelRegistry(max_batch=8, max_latency_ms=2.0,
                             queue_limit=128)
    registry.deploy("classifier", v1_path)
    server = ModelServer(registry)
    versions_served = []
    try:
        if verbose:
            print(f"serving at {server.url}")
        status, body = _post_predict(server.port, "classifier",
                                     x[:2].tolist())
        assert status == 200, body
        versions_served.append(body["model_version"])
        if verbose:
            print(f"v{body['model_version']} prediction: "
                  f"{np.argmax(body['predictions'], -1)}")

        registry.deploy("classifier", v2_path)     # hot swap, zero drops
        status, body = _post_predict(server.port, "classifier",
                                     x[:2].tolist())
        assert status == 200, body
        versions_served.append(body["model_version"])

        registry.rollback("classifier")            # v1 zip → version 3
        status, body = _post_predict(server.port, "classifier",
                                     x[:2].tolist())
        assert status == 200, body
        versions_served.append(body["model_version"])

        conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                          timeout=30)
        conn.request("GET", "/v1/models")
        models = json.loads(conn.getresponse().read())["models"]
        conn.close()
        if verbose:
            print(f"versions served: {versions_served}")
            print(f"registry: {models[0]['name']} "
                  f"v{models[0]['version']} ({models[0]['status']}), "
                  f"history {[h['version'] for h in models[0]['history']]}")
    finally:
        server.stop()
        registry.close()
    return {"versions_served": versions_served,
            "final_version": versions_served[-1],
            "workdir": workdir}


if __name__ == "__main__":
    main()
