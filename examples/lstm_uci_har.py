"""LSTM sequence classification on UCI-HAR
(dl4j-examples ``UCISequenceClassification``)."""

from deeplearning4j_tpu.data import datasets
from deeplearning4j_tpu.models import lstm_classifier


def main(epochs: int = 2, batch_size: int = 64, n_synthetic: int = 1200,
         verbose: bool = True):
    net = lstm_classifier().init()
    train = datasets.uci_har(batch_size=batch_size, train=True,
                             n_synthetic=n_synthetic)
    test = datasets.uci_har(batch_size=128, train=False,
                            n_synthetic=n_synthetic)
    net.fit(train, epochs=epochs)
    ev = net.evaluate(test)
    if verbose:
        print(ev.stats())
    return ev.accuracy()


if __name__ == "__main__":
    main()
