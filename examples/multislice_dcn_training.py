"""Multi-slice training with compressed cross-slice gradient exchange
(the SharedTrainingMaster workflow: within a slice gradients ride ICI as
dense psum; BETWEEN slices each leader threshold-sparsifies its gradient
with error feedback and exchanges wire messages over DCN).

Needs >= 4 devices: run under the test mesh
(``XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu``)
or any real multi-device topology.
"""

import jax
import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.dcn_trainer import MultiSliceTrainer
from deeplearning4j_tpu.train import Sgd


def main(steps: int = 10, n_slices: int = 2, data_per_slice: int = 2,
         verbose: bool = True):
    if len(jax.devices()) < n_slices * data_per_slice:
        raise SystemExit(f"need {n_slices * data_per_slice} devices "
                         f"(have {len(jax.devices())})")

    conf = (NeuralNetConfiguration.builder().seed(7).updater(Sgd(0.1))
            .weight_init("xavier").list()
            .layer(DenseLayer(n_out=32, activation="tanh"))
            .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(16)).build())
    net = MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 16)).astype(np.float32)
    w = rng.normal(size=(16, 4)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[np.argmax(x @ w, -1)]
    batch = DataSet(x, y)

    trainer = MultiSliceTrainer(net, n_slices=n_slices,
                                data_per_slice=data_per_slice)
    try:
        key = jax.random.key(0)
        losses = []
        for step in range(steps):
            key, sub = jax.random.split(key)
            losses.append(trainer.fit_batch(batch, sub))
            if verbose:
                ws = trainer.last_wire_stats[0]
                print(f"step {step}: loss {losses[-1]:.4f}  "
                      f"wire {ws['wire_bytes']}B vs dense "
                      f"{ws['dense_bytes']}B ({ws['compression']:.1f}x), "
                      f"divergence {trainer.max_param_divergence():.1e}")
        trainer.collect()          # synchronized params back onto net
    finally:
        trainer.close()
    return losses


if __name__ == "__main__":
    main()
