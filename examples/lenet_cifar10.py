"""LeNet on CIFAR-10 — conv-net training
(dl4j-examples ``LeNetMNIST`` / ``Cifar10Classification``)."""

from deeplearning4j_tpu.data import datasets
from deeplearning4j_tpu.models import lenet


def main(epochs: int = 1, batch_size: int = 128, n_synthetic: int = 2000,
         verbose: bool = True):
    net = lenet(height=32, width=32, channels=3, num_classes=10).init()
    train = datasets.cifar10(batch_size=batch_size, train=True,
                             n_synthetic=n_synthetic)
    test = datasets.cifar10(batch_size=256, train=False,
                            n_synthetic=n_synthetic)
    net.fit(train, epochs=epochs)
    ev = net.evaluate(test)
    if verbose:
        print(ev.stats())
    return ev.accuracy()


if __name__ == "__main__":
    main()
