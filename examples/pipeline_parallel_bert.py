"""Pipeline-parallel BERT training with the 1F1B schedule: the model
splits into heterogeneous stages (embeddings / encoder blocks / encoder+
MLM head), each stage owned by one device on the ``stage`` mesh axis;
activations ride a ring ppermute and backward ticks start as soon as
their cotangents exist (at most S-s microbatches stashed per stage).

Needs >= 4 devices (see multislice example for the virtual-mesh flags).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.models import bert
from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.parallel.pipeline_stages import pipeline_train_step


def main(steps: int = 3, n_stages: int = 4, verbose: bool = True):
    if len(jax.devices()) < n_stages:
        raise SystemExit(f"need {n_stages} devices")
    config = dataclasses.replace(bert.BertConfig.tiny(vocab_size=256),
                                 num_layers=n_stages)
    params = bert.init_params(config, jax.random.key(0))
    stage_fns, stage_params = bert.pipeline_stages(config, params, n_stages)
    mesh = make_mesh(data=1, pipe=n_stages,
                     devices=jax.devices()[:n_stages])

    rng = np.random.default_rng(0)
    b, t = 8, 16
    ids_np = rng.integers(5, 256, (b, t)).astype(np.float32)
    ids = jnp.asarray(ids_np)
    # MLM objective: reconstruct the input tokens at every position
    # (a full-visibility denoising toy; bert_mlm_finetune.py shows real
    # 15%-masked batches)
    packed = jnp.asarray(np.stack(
        [ids_np, np.ones((b, t), np.float32)], axis=-1))

    lr = 1e-2
    losses = []
    for step in range(steps):
        with mesh:
            loss, grads = pipeline_train_step(
                stage_fns, stage_params, ids, packed,
                bert.mlm_loss_from_logits, mesh, n_microbatches=4)
        stage_params = [jax.tree_util.tree_map(lambda p, g: p - lr * g, sp, g)
                        for sp, g in zip(stage_params, grads)]
        losses.append(float(loss))
        if verbose:
            print(f"step {step}: pipelined MLM loss {losses[-1]:.4f}")
    return losses


if __name__ == "__main__":
    main()
