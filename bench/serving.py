#!/usr/bin/env python
"""CPU micro-bench: batch-1 sequential serving vs dynamic micro-batching.

Measures the serve subsystem's two effects without a TPU:

* **throughput/latency** — 16 closed-loop clients each issue ragged
  requests (1–4 rows).  Sequential mode answers each request with its
  own ``net.output`` call (one dispatch per request); dynamic mode
  routes the same traffic through ``serve.InferenceEngine``, which
  coalesces concurrent requests into deadline-bounded micro-batches —
  fewer, larger dispatches → higher requests/sec and a far tighter p99.
* **recompile guard** — the ragged sizes compile one XLA program per
  DISTINCT request shape on the sequential path, *during* serving (the
  p99 cliffs); the engine's bucket set is finite and precompiled up
  front, so ragged traffic never compiles on the serving path.
* **load_sweep (ISSUE 13)** — closed-loop offered load rising ~10x
  against one router-managed model while the queue-depth autoscaler
  grows replicas 1→4, with one fan-out hot-swap and one all-replica
  rollback landing under load: p99 held within 2x of the 1x baseline,
  zero dropped or garbled responses (own subprocess, like cold_start).

Run standalone (``python bench/serving.py``) or via the ``serving``
record in ``bench.py`` (subprocess pinned to ``JAX_PLATFORMS=cpu`` —
the record stays measurable when the TPU tunnel is down, like
``feed_overlap``).  Prints ONE json line.
"""

import json
import os
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

N_CLIENTS = 16
REQS_PER_CLIENT = 15
N_FEATURES = 512
HIDDEN = 512
CLASSES = 16
MAX_ROWS = 4          # ragged request sizes 1..MAX_ROWS


def _build_net(hidden=HIDDEN, depth=1, n_features=N_FEATURES, seed=7):
    from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.train import Sgd
    builder = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(0.1))
               .list())
    for _ in range(depth):
        builder = builder.layer(DenseLayer(n_out=hidden, activation="relu"))
    conf = (builder
            .layer(OutputLayer(n_out=CLASSES, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(n_features)).build())
    return MultiLayerNetwork(conf).init()


def _requests(seed=0):
    rng = np.random.default_rng(seed)
    sizes = rng.integers(1, MAX_ROWS + 1, N_CLIENTS * REQS_PER_CLIENT)
    return [rng.normal(size=(int(n), N_FEATURES)).astype(np.float32)
            for n in sizes]


def _percentiles(latencies):
    ordered = sorted(latencies)

    def pick(q):
        return ordered[min(len(ordered) - 1, int(q * len(ordered)))]

    return {"p50_ms": round(1e3 * pick(0.50), 3),
            "p99_ms": round(1e3 * pick(0.99), 3)}


def _run_clients(answer, reqs):
    """Closed-loop load: N_CLIENTS threads, each waits for its previous
    answer before sending the next request."""
    latencies = []
    lock = threading.Lock()
    chunks = [reqs[i::N_CLIENTS] for i in range(N_CLIENTS)]

    def client(mine):
        for x in mine:
            t1 = time.perf_counter()
            answer(x)
            dt = time.perf_counter() - t1
            with lock:
                latencies.append(dt)

    threads = [threading.Thread(target=client, args=(c,)) for c in chunks]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    return latencies, wall


def bench_sequential(net, reqs):
    from deeplearning4j_tpu.train import step_cache
    # warm the smallest shape only — recompiles for the OTHER ragged
    # shapes land in the measured pass (that is the story)
    np.asarray(net.output(reqs[0]))
    from deeplearning4j_tpu.obs import costmodel
    costmodel.drain()   # warm shape's background analysis out of the region
    lat, wall = _run_clients(lambda x: np.asarray(net.output(x)), reqs)
    return {"requests_per_s": round(len(reqs) / wall, 1),
            **_percentiles(lat),
            "compiled_programs": step_cache.jit_cache_entries(
                net._output_fn)}


def bench_dynamic(net, reqs, name="bench"):
    from deeplearning4j_tpu.serve import InferenceEngine
    engine = InferenceEngine(net, name=name, max_batch=32,
                             max_latency_ms=1.0, buckets=(8, 16, 32),
                             queue_limit=4 * N_CLIENTS)
    try:
        # the production state: the WHOLE bucket set is precompilable up
        # front (that is the point of bounded buckets) — ragged traffic
        # then never compiles.  The sequential path has no equivalent:
        # every distinct request shape is a cold compile.
        rng = np.random.default_rng(1)
        width = reqs[0].shape[1]
        for bucket in engine.buckets:
            engine.predict(rng.normal(size=(bucket, width))
                           .astype(np.float32), timeout_s=120)
        from deeplearning4j_tpu.obs import costmodel
        costmodel.drain()   # bucket analyses (and sequential's leftovers)
        lat, wall = _run_clients(
            lambda x: engine.predict(x, timeout_s=120), reqs)
        return {"requests_per_s": round(len(reqs) / wall, 1),
                **_percentiles(lat),
                "compiled_programs": engine.compiled_programs,
                "buckets_touched": list(engine.buckets)}
    finally:
        engine.shutdown()


def bench_quantized():
    """ISSUE 11: the quantized-serve row — ONE ragged closed-loop
    traffic mix (its own, weight-bound: chunkier/wider than the
    headline rows') through a bf16 engine and an int8-quantized engine
    of the same architecture (int8 weights via ``nn.quantize``,
    activations bf16, dequant fused into the matmul).  On TPU the int8
    win is HBM bytes (weights stream 1 byte/param); on this CPU rig the
    same program graph wins because XLA's bf16 dot is slower than the
    int8-widening dot — either way the row is req/s + p99, int8 vs
    bf16, plus the cost-model stamps showing the int8 program's higher
    arithmetic intensity (cost_analysis counts the int8 param bytes)."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.config import DTypePolicy, set_dtype_policy
    from deeplearning4j_tpu.nn import quantize
    from deeplearning4j_tpu.obs import costmodel

    # serving-deployment policy: weights SHIP as bf16 (param_dtype
    # bf16 — no per-call f32→bf16 weight convert; inference holds no
    # optimizer state, so the train-side reason for f32 params is moot)
    set_dtype_policy(DTypePolicy(param_dtype=jnp.bfloat16,
                                 compute_dtype=jnp.bfloat16,
                                 output_dtype=jnp.bfloat16))
    try:
        # weight-bound config (wider + deeper than the headline row, and
        # chunkier requests): serving cost is dominated by running the
        # weight matrices, which is the regime the int8 path exists for —
        # with a ~1 ms forward the batcher's deadline flush would drown
        # the per-dispatch difference in scheduler noise
        width = 1024
        net = _build_net(hidden=width, depth=6, n_features=width)
        rng = np.random.default_rng(5)
        sizes = rng.integers(4, 17, N_CLIENTS * 20)
        reqs = [rng.normal(size=(int(n), width)).astype(np.float32)
                for n in sizes]
        calib = [reqs[0], reqs[1]]
        qnet = quantize.quantize_net(net, calibration=calib)
        report = qnet.quantization_
        bf16 = bench_dynamic(net, reqs, name="bench_bf16")
        int8 = bench_dynamic(qnet, reqs, name="bench_int8")
        # stamp pass: the engines' background analyses race the traffic
        # (a duplicate XLA compile competing with 16 client threads may
        # land only after the run ends, and an un-redispatched bucket
        # never observes) — so stamp each variant's program
        # synchronously through the step-cached forward, one fixed
        # bucket, analysis + one fenced measured call
        costmodel.drain()
        import time as _time

        import jax.numpy as jnp
        from deeplearning4j_tpu.serve import InferenceEngine
        kind = "serve_forward:MultiLayerNetwork"
        bucket = 32
        xpad = np.zeros((bucket, width), np.float32)
        for model, suffix in ((net, ""), (qnet, ":int8")):
            eng = InferenceEngine(model, name="stamp", max_batch=bucket,
                                  buckets=(bucket,), max_latency_ms=0.5)
            try:
                eng.predict(xpad, timeout_s=120)       # warm the trace
                fwd = eng._fwd
                args = (model.params_, model.state_, jnp.asarray(xpad),
                        None)
                sigk = ("stamp", suffix)
                if costmodel.should_analyze(fwd, sig=sigk):
                    costmodel.analyze_jitted(
                        fwd, costmodel.abstractify(args),
                        kind=kind + suffix, sig=sigk)
                t0 = _time.perf_counter()
                np.asarray(fwd(*args))                 # fenced measure
                costmodel.observe_step(fwd, _time.perf_counter() - t0,
                                       sig=sigk)
            finally:
                eng.shutdown()
        perf_bf16 = costmodel.bench_detail(kind=kind) or {}
        perf_int8 = costmodel.bench_detail(kind=kind + ":int8") or {}
        ai_bf16 = perf_bf16.get("arith_intensity")
        ai_int8 = perf_int8.get("arith_intensity")
        speedup = round(int8["requests_per_s"]
                        / max(bf16["requests_per_s"], 1e-9), 2)
        return {
            "bf16": bf16,
            "int8": int8,
            "speedup": speedup,
            "p99_ratio": round(int8["p99_ms"] / max(bf16["p99_ms"], 1e-9),
                               2),
            "wins": bool(speedup >= 1.3
                         or int8["p99_ms"] < bf16["p99_ms"]),
            "arith_intensity_bf16": ai_bf16,
            "arith_intensity_int8": ai_int8,
            "intensity_gain": (round(ai_int8 / ai_bf16, 2)
                               if ai_bf16 and ai_int8 else None),
            "quantization": report.to_dict(),
            "note": ("same traffic, same architecture; int8 weights + "
                     "bf16 activations vs bf16 end-to-end — the int8 "
                     "program streams 1 byte/weight (see "
                     "arith_intensity_int8 vs _bf16 from "
                     "xla_cost_analysis)"),
        }
    finally:
        set_dtype_policy(DTypePolicy.f32())


# ------------------------------------------------------------ load sweep
SWEEP_WIDTH = 1024       # weight-heavy forward (~10ms/dispatch on CPU):
SWEEP_DEPTH = 6          # one replica saturates, so scaling is visible
SWEEP_POOL = 32          # oracle input rows (requests slice into these)
SWEEP_MAX_ROWS = 4


def _sweep_stage(registry, router, x_pool, clients, reqs_per_client,
                 mid_stage=None):
    """One closed-loop load stage: ``clients`` threads, each waiting
    for its previous answer before the next request (offered load
    scales with the client count).  Every response is checked later
    against the per-version oracles; sheds are counted by lane.
    ``mid_stage`` (the fan-out swap / rollback hook) fires once while
    the clients are in full flight."""
    from deeplearning4j_tpu.serve import Overloaded
    results, latencies, errors = [], [], []
    sheds = {"interactive": 0, "batch": 0}
    lock = threading.Lock()

    def client(cid):
        rng = np.random.default_rng(1000 + cid)
        lane = "batch" if cid % 4 == 3 else "interactive"
        tenant = "paid" if cid % 2 else "free"
        for req_idx in range(reqs_per_client):
            i = int(rng.integers(0, SWEEP_POOL - SWEEP_MAX_ROWS))
            n = int(rng.integers(1, SWEEP_MAX_ROWS + 1))
            t1 = time.perf_counter()
            try:
                out = registry.predict("m", x_pool[i:i + n], timeout_s=60,
                                       tenant=tenant, lane=lane)
            except Overloaded:
                with lock:
                    sheds[lane] += 1
                continue
            except BaseException as e:   # a DROPPED request — must be 0
                with lock:
                    errors.append(repr(e)[:200])
                continue
            dt = time.perf_counter() - t1
            with lock:
                # latency measures STEADY-STATE closed-loop serving:
                # every client's first round lands on a synchronized
                # burst into an empty queue (an artifact of the stage
                # harness, not of offered load) — answered/garble checks
                # still cover it
                if req_idx > 0:
                    latencies.append(dt)
                results.append((i, n, np.asarray(out)))

    threads = [threading.Thread(target=client, args=(c,))
               for c in range(clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    event = None
    if mid_stage is not None:
        time.sleep(0.15)         # clients are in full flight
        t1 = time.perf_counter()
        event = mid_stage()
        event["duration_ms"] = round(1e3 * (time.perf_counter() - t1), 1)
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    record = {
        "clients": clients,
        "offered": clients * reqs_per_client,
        "answered": len(results),
        "requests_per_s": round(len(results) / max(wall, 1e-9), 1),
        **(_percentiles(latencies) if latencies
           else {"p50_ms": None, "p99_ms": None}),
        "shed_by_lane": dict(sheds),
        "errors": errors,
        "replicas": router.replicas,
    }
    if event is not None:
        record["event"] = event
    return record, results


def bench_load_sweep():
    """ISSUE 13: traffic-scale serving.  Closed-loop offered load rises
    ~10x (2 → 20 clients) against ONE router-managed model while the
    queue-depth autoscaler grows the replica set 1 → 4; mid-sweep the
    deploy plane runs one verified fan-out hot-swap (v1 → v2) and one
    all-replica rollback UNDER load.  Reports req/s, p50/p99, sheds by
    priority lane, and the replica count per stage.  Acceptance: p99 at
    10x offered load held within 2x of the single-replica 1x baseline,
    zero dropped and zero garbled responses through both swap events —
    every answered row must equal one version's oracle output."""
    import tempfile

    from deeplearning4j_tpu.obs import costmodel
    from deeplearning4j_tpu.serve import (AdmissionControl, Autoscaler,
                                          AutoscaleConfig, Lane,
                                          ModelRegistry, ReplicaRouter)
    net1 = _build_net(hidden=SWEEP_WIDTH, depth=SWEEP_DEPTH,
                      n_features=SWEEP_WIDTH, seed=11)
    rng = np.random.default_rng(9)
    # v2 = SAME architecture (same config sha → the fan-out swap shares
    # the step-cached compiled forward: zero recompiles under load),
    # different weights — one fit epoch moves every layer
    net2 = _build_net(hidden=SWEEP_WIDTH, depth=SWEEP_DEPTH,
                      n_features=SWEEP_WIDTH, seed=11)
    from deeplearning4j_tpu.data.iterators import ArrayDataSetIterator
    xs = rng.normal(size=(64, SWEEP_WIDTH)).astype(np.float32)
    ys = np.eye(CLASSES, dtype=np.float32)[
        rng.integers(0, CLASSES, 64)]
    net2.fit(ArrayDataSetIterator(xs, ys, 32), epochs=1)
    x_pool = rng.normal(size=(SWEEP_POOL, SWEEP_WIDTH)).astype(np.float32)
    oracle = {1: np.asarray(net1.output(x_pool)),
              2: np.asarray(net2.output(x_pool))}
    workdir = tempfile.mkdtemp(prefix="tpudl_loadsweep_")
    p1 = os.path.join(workdir, "v1.zip")
    p2 = os.path.join(workdir, "v2.zip")
    net1.save(p1)
    net2.save(p2)

    # engine knobs: the stack defaults (docs/serving.md) — at 1x load
    # latency pays the 5ms batching deadline, under load batches
    # size-flush and the deadline never binds
    registry = ModelRegistry(max_batch=16, queue_limit=24)
    registry.deploy("m", p1)
    router = ReplicaRouter(
        registry, "m", replicas=1, min_replicas=1, max_replicas=4,
        admission=AdmissionControl(
            lanes=[Lane("interactive", 0, shed_at=1.0),
                   Lane("batch", 1, shed_at=0.15)],
            default_lane="interactive"))
    autoscaler = None
    try:
        # warm every bucket once — all replicas share the step-cached
        # forward, so this covers the whole (current and future) fleet
        for bucket in (1, 2, 4, 8, 16):
            router.predict(x_pool[:bucket], timeout_s=120)
        costmodel.drain()
        # replica add/retire cost: the scale-up-in-milliseconds claim,
        # measured (shared compiled forward — a thread and a queue)
        t0 = time.perf_counter()
        router.add_replica()
        add_ms = round(1e3 * (time.perf_counter() - t0), 2)
        router.retire_replica()

        # baseline: 1x offered load, single replica, autoscaler off
        # (enough rounds that its p99 is a percentile, not one outlier)
        baseline, results = _sweep_stage(registry, router, x_pool,
                                         clients=2, reqs_per_client=80)
        all_results = list(results)

        autoscaler = Autoscaler(router, AutoscaleConfig(
            scale_up_at=0.05, scale_down_at=0.01, poll_s=0.01,
            up_cooldown_s=0.01, down_cooldown_s=60.0))
        stages = [baseline]
        # the deploy-plane events land in the RAMP stages (under live
        # load, while the autoscaler is growing the fleet); the 10x
        # stage then measures pure scaled-out serving
        for clients, rpc, mid in (
                (6, 25, lambda: {"fan_out_swap":
                                 router.deploy(p2).version}),
                (12, 20, lambda: {"rollback":
                                  registry.rollback("m").version}),
                (20, 20, None)):
            record, results = _sweep_stage(registry, router, x_pool,
                                           clients, rpc, mid_stage=mid)
            stages.append(record)
            all_results.extend(results)
    finally:
        if autoscaler is not None:
            autoscaler.close()
        registry.close()

    garbled = 0
    for i, n, rows in all_results:
        if not any(np.allclose(rows, oracle[v][i:i + n],
                               rtol=1e-4, atol=1e-4) for v in oracle):
            garbled += 1
    dropped = sum(len(s["errors"]) for s in stages)
    shed_by_lane = {
        lane: sum(s["shed_by_lane"].get(lane, 0) for s in stages)
        for lane in ("interactive", "batch")}
    p99_ratio = (round(stages[-1]["p99_ms"] / baseline["p99_ms"], 2)
                 if stages[-1]["p99_ms"] and baseline["p99_ms"] else None)
    held = bool(p99_ratio is not None and p99_ratio <= 2.0)
    return {
        "metric": "load_sweep_p99_ratio_at_10x_load",
        "value": p99_ratio,
        "offered_load_x": round(stages[-1]["clients"]
                                / baseline["clients"], 1),
        "stages": stages,
        "replicas_per_stage": [s["replicas"] for s in stages],
        "replica_add_ms": add_ms,
        "shed_by_lane": shed_by_lane,
        "p99_held_2x": held,
        "dropped": dropped,
        "garbled": garbled,
        "zero_dropped_or_garbled": bool(dropped == 0 and garbled == 0),
        "wins": bool(held and dropped == 0 and garbled == 0
                     and max(s["replicas"] for s in stages) >= 3),
        "note": ("closed-loop clients against one router-managed model; "
                 "offered load ~10x while the queue-depth autoscaler "
                 "grows replicas (scale-up = a thread + a queue: the "
                 "compiled forward is shared process-wide); one fan-out "
                 "hot-swap and one all-replica rollback land mid-sweep "
                 "under load — every response row must equal one "
                 "version's oracle output"),
    }


_SWEEP_CHILD_FLAG = "--load-sweep-child"


def _spawn_load_sweep():
    """Run the load sweep in a FRESH subprocess: the headline rows
    leave behind compiled programs, drained engines and background
    analysis threads whose scheduler noise lands squarely in a p99
    measurement — the sweep gets the same process isolation the
    cold-start record uses."""
    import subprocess
    here = os.path.abspath(__file__)
    repo_root = os.path.dirname(os.path.dirname(here))
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": repo_root + os.pathsep
           + os.environ.get("PYTHONPATH", "")}
    proc = subprocess.run(
        [sys.executable, here, _SWEEP_CHILD_FLAG],
        capture_output=True, text=True, timeout=600, env=env)
    if proc.returncode != 0:
        raise RuntimeError(f"load-sweep child failed rc={proc.returncode}: "
                           f"{proc.stderr[-2000:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


COLD_BUCKET = 16
COLD_WIDTH = 128
COLD_DEPTH = 10        # stacked LSTMs: XLA's slowest-compiling shape
COLD_TIMESTEPS = 32    # per parameter byte — compile dominates restore,
                       # which is the regime every real TPU model is in

_COLD_CHILD_FLAG = "--cold-child"


def _cold_net():
    """The cold-start model: a deep LSTM stack.  Recurrent scans are
    the worst-case XLA compile per weight byte on CPU, which makes the
    restart cost structure match real TPU serving (compile >> weight
    load) at bench-friendly sizes."""
    from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import LSTM, RnnOutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.train import Sgd
    builder = (NeuralNetConfiguration.builder().seed(7).updater(Sgd(0.1))
               .list())
    for _ in range(COLD_DEPTH):
        builder = builder.layer(LSTM(n_out=COLD_WIDTH, activation="tanh"))
    conf = (builder
            .layer(RnnOutputLayer(n_out=CLASSES, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(InputType.recurrent(COLD_WIDTH,
                                                COLD_TIMESTEPS)).build())
    return MultiLayerNetwork(conf).init()


def _cold_child(zip_path):
    """One 'restarted server': deploy the zip and answer ONE request,
    timing restore→ready and ready→first-response.  Runs in its own
    process (a restart is a process event; in-process simulation would
    hit warm jit caches and lie).  Prints one json line."""
    import numpy as np

    from deeplearning4j_tpu.obs.registry import get_registry
    from deeplearning4j_tpu.serve.registry import ModelRegistry
    x = np.zeros((COLD_BUCKET, COLD_TIMESTEPS, COLD_WIDTH), np.float32)
    t0 = time.perf_counter()
    registry = ModelRegistry(max_batch=COLD_BUCKET, buckets=(COLD_BUCKET,))
    entry = registry.deploy("m", zip_path)
    deploy_s = time.perf_counter() - t0
    out = np.asarray(registry.predict("m", x, timeout_s=300))
    total_s = time.perf_counter() - t0
    assert out.shape[0] == COLD_BUCKET
    reg = get_registry()
    print(json.dumps({
        "deploy_s": round(deploy_s, 4),
        "first_response_s": round(total_s - deploy_s, 4),
        "total_s": round(total_s, 4),
        "compiled_programs": entry.engine.compiled_programs,
        "warm_programs": entry.engine.warm_programs,
        "artifacts_loaded": reg.counter(
            "tpudl_compile_artifacts_loaded_total").value,
        "artifact_rejects": reg.counter(
            "tpudl_compile_artifact_rejects_total").value,
    }))
    registry.close()
    return 0


def _spawn_cold_child(zip_path):
    import subprocess
    here = os.path.abspath(__file__)
    repo_root = os.path.dirname(os.path.dirname(here))
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           # clean measurement: no background duplicate-compile racing
           # the timed window in either child
           "DL4J_TPU_COSTMODEL": "0",
           # prepend, never overwrite — the parent's PYTHONPATH may
           # carry required shims (multichip.py convention)
           "PYTHONPATH": repo_root + os.pathsep
           + os.environ.get("PYTHONPATH", "")}
    proc = subprocess.run(
        [sys.executable, here, _COLD_CHILD_FLAG, zip_path],
        capture_output=True, text=True, timeout=600, env=env)
    if proc.returncode != 0:
        raise RuntimeError(f"cold-start child failed rc={proc.returncode}: "
                           f"{proc.stderr[-2000:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def bench_cold_start():
    """ISSUE 12: restart → first served response, before/after the
    compiled-artifact store (train/artifact_store).  The same model zip
    is deployed by two fresh subprocesses: COLD (no artifacts — the
    first request pays live XLA compilation) and WARM (the zip carries
    AOT-serialized executables baked at 'deploy time' by the parent —
    the restarted server deserializes and answers with zero JIT on the
    request path).  CPU-measurable, so the record survives a down TPU
    tunnel; on TPU the cold side only grows (bigger programs, slower
    compiles), so the CPU ratio is a floor."""
    import tempfile

    from deeplearning4j_tpu.train import artifact_store
    net = _cold_net()
    workdir = tempfile.mkdtemp(prefix="tpudl_coldstart_")
    zip_path = os.path.join(workdir, "model.zip")
    net.save(zip_path)
    cold = _spawn_cold_child(zip_path)
    t0 = time.perf_counter()
    baked = artifact_store.ensure_zip_artifacts(net=net, path=zip_path,
                                                buckets=(COLD_BUCKET,))
    bake_s = time.perf_counter() - t0
    warm = _spawn_cold_child(zip_path)
    speedup = round(cold["total_s"] / max(warm["total_s"], 1e-9), 2)
    first_response_speedup = round(
        cold["first_response_s"] / max(warm["first_response_s"], 1e-9), 2)
    return {
        "metric": "cold_start_restart_to_first_response_s",
        "value": warm["total_s"],
        "cold": cold,
        "warm": warm,
        # restart → first served response end to end (verified restore
        # is common to both sides; the store removes the compile term)
        "speedup": speedup,
        # the request-path story: what the first caller actually waits
        # after the server reports ready — live XLA compile vs a warm
        # dispatch of the deserialized executable
        "first_response_speedup": first_response_speedup,
        "programs_baked": baked,
        "bake_s": round(bake_s, 3),
        "zero_jit_after_warm": bool(warm["compiled_programs"] == 0
                                    and warm["warm_programs"] >= 1),
        "wins": bool(first_response_speedup >= 5.0 and speedup > 1.0),
        "note": ("restart → first served response, measured inside two "
                 "fresh subprocesses deploying the SAME zip; warm path "
                 "deserializes AOT-compiled executables from the "
                 "checkpoint's artifact store instead of compiling on "
                 "first traffic"),
    }


def main():
    net = _build_net()
    reqs = _requests()
    sequential = bench_sequential(net, reqs)
    dynamic = bench_dynamic(_build_net(), reqs)
    try:    # int8 vs bf16 through the same engine machinery
        quantized = bench_quantized()
    except Exception as e:   # the headline rows survive a quantize break
        quantized = {"error": f"{type(e).__name__}: {e}"[:200]}
    try:    # restart → first response, cold vs artifact-warmed (ISSUE 12)
        cold_start = bench_cold_start()
    except Exception as e:   # headline rows survive a cold-start break
        cold_start = {"error": f"{type(e).__name__}: {e}"[:200]}
    try:    # 10x load vs replica autoscaling + fan-out swaps (ISSUE 13)
        load_sweep = _spawn_load_sweep()
    except Exception as e:   # headline rows survive a sweep break
        load_sweep = {"error": f"{type(e).__name__}: {e}"[:200]}
    # roofline stamp: the engine's dispatch loop analyzed its compiled
    # forward through cost_analysis and observed per-batch device time,
    # so the serving record self-reports MFU/HBM/intensity (CPU-
    # measurable — survives a down TPU tunnel)
    from deeplearning4j_tpu.obs import costmodel
    costmodel.drain()   # flush any still-queued background analysis
    perf = costmodel.bench_detail() or {}
    out = {
        "metric": "serving_requests_per_s",
        "value": dynamic["requests_per_s"],
        "clients": N_CLIENTS,
        "requests": len(reqs),
        "ragged_rows": [1, MAX_ROWS],
        "sequential": sequential,
        "dynamic": dynamic,
        "quantized": quantized,
        "cold_start": cold_start,
        "load_sweep": load_sweep,
        "mfu": perf.get("mfu"),
        "hbm_util": perf.get("hbm_util"),
        "arith_intensity": perf.get("arith_intensity"),
        "perf": perf,
        "throughput_ratio": round(
            dynamic["requests_per_s"]
            / max(sequential["requests_per_s"], 1e-9), 2),
        "note": ("closed-loop clients on CPU; sequential pays one "
                 "dispatch (and one compile per distinct ragged shape), "
                 "dynamic micro-batching coalesces concurrent requests "
                 "into bucket-padded batches"),
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == _COLD_CHILD_FLAG:
        sys.exit(_cold_child(sys.argv[2]))
    if len(sys.argv) > 1 and sys.argv[1] == _SWEEP_CHILD_FLAG:
        print(json.dumps(bench_load_sweep()))
        sys.exit(0)
    sys.exit(main())
