#!/usr/bin/env python
"""CPU micro-bench: batch-1 sequential serving vs dynamic micro-batching.

Measures the serve subsystem's two effects without a TPU:

* **throughput/latency** — 16 closed-loop clients each issue ragged
  requests (1–4 rows).  Sequential mode answers each request with its
  own ``net.output`` call (one dispatch per request); dynamic mode
  routes the same traffic through ``serve.InferenceEngine``, which
  coalesces concurrent requests into deadline-bounded micro-batches —
  fewer, larger dispatches → higher requests/sec and a far tighter p99.
* **recompile guard** — the ragged sizes compile one XLA program per
  DISTINCT request shape on the sequential path, *during* serving (the
  p99 cliffs); the engine's bucket set is finite and precompiled up
  front, so ragged traffic never compiles on the serving path.

Run standalone (``python bench/serving.py``) or via the ``serving``
record in ``bench.py`` (subprocess pinned to ``JAX_PLATFORMS=cpu`` —
the record stays measurable when the TPU tunnel is down, like
``feed_overlap``).  Prints ONE json line.
"""

import json
import os
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

N_CLIENTS = 16
REQS_PER_CLIENT = 15
N_FEATURES = 512
HIDDEN = 512
CLASSES = 16
MAX_ROWS = 4          # ragged request sizes 1..MAX_ROWS


def _build_net():
    from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.train import Sgd
    conf = (NeuralNetConfiguration.builder().seed(7).updater(Sgd(0.1))
            .list()
            .layer(DenseLayer(n_out=HIDDEN, activation="relu"))
            .layer(OutputLayer(n_out=CLASSES, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(N_FEATURES)).build())
    return MultiLayerNetwork(conf).init()


def _requests(seed=0):
    rng = np.random.default_rng(seed)
    sizes = rng.integers(1, MAX_ROWS + 1, N_CLIENTS * REQS_PER_CLIENT)
    return [rng.normal(size=(int(n), N_FEATURES)).astype(np.float32)
            for n in sizes]


def _percentiles(latencies):
    ordered = sorted(latencies)

    def pick(q):
        return ordered[min(len(ordered) - 1, int(q * len(ordered)))]

    return {"p50_ms": round(1e3 * pick(0.50), 3),
            "p99_ms": round(1e3 * pick(0.99), 3)}


def _run_clients(answer, reqs):
    """Closed-loop load: N_CLIENTS threads, each waits for its previous
    answer before sending the next request."""
    latencies = []
    lock = threading.Lock()
    chunks = [reqs[i::N_CLIENTS] for i in range(N_CLIENTS)]

    def client(mine):
        for x in mine:
            t1 = time.perf_counter()
            answer(x)
            dt = time.perf_counter() - t1
            with lock:
                latencies.append(dt)

    threads = [threading.Thread(target=client, args=(c,)) for c in chunks]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    return latencies, wall


def bench_sequential(net, reqs):
    from deeplearning4j_tpu.train import step_cache
    # warm the smallest shape only — recompiles for the OTHER ragged
    # shapes land in the measured pass (that is the story)
    np.asarray(net.output(reqs[0]))
    from deeplearning4j_tpu.obs import costmodel
    costmodel.drain()   # warm shape's background analysis out of the region
    lat, wall = _run_clients(lambda x: np.asarray(net.output(x)), reqs)
    return {"requests_per_s": round(len(reqs) / wall, 1),
            **_percentiles(lat),
            "compiled_programs": step_cache.jit_cache_entries(
                net._output_fn)}


def bench_dynamic(net, reqs):
    from deeplearning4j_tpu.serve import InferenceEngine
    engine = InferenceEngine(net, name="bench", max_batch=32,
                             max_latency_ms=1.0, buckets=(8, 16, 32),
                             queue_limit=4 * N_CLIENTS)
    try:
        # the production state: the WHOLE bucket set is precompilable up
        # front (that is the point of bounded buckets) — ragged traffic
        # then never compiles.  The sequential path has no equivalent:
        # every distinct request shape is a cold compile.
        rng = np.random.default_rng(1)
        for bucket in engine.buckets:
            engine.predict(rng.normal(size=(bucket, N_FEATURES))
                           .astype(np.float32), timeout_s=120)
        from deeplearning4j_tpu.obs import costmodel
        costmodel.drain()   # bucket analyses (and sequential's leftovers)
        lat, wall = _run_clients(
            lambda x: engine.predict(x, timeout_s=120), reqs)
        return {"requests_per_s": round(len(reqs) / wall, 1),
                **_percentiles(lat),
                "compiled_programs": engine.compiled_programs,
                "buckets_touched": list(engine.buckets)}
    finally:
        engine.shutdown()


def main():
    net = _build_net()
    reqs = _requests()
    sequential = bench_sequential(net, reqs)
    dynamic = bench_dynamic(_build_net(), reqs)
    # roofline stamp: the engine's dispatch loop analyzed its compiled
    # forward through cost_analysis and observed per-batch device time,
    # so the serving record self-reports MFU/HBM/intensity (CPU-
    # measurable — survives a down TPU tunnel)
    from deeplearning4j_tpu.obs import costmodel
    costmodel.drain()   # flush any still-queued background analysis
    perf = costmodel.bench_detail() or {}
    out = {
        "metric": "serving_requests_per_s",
        "value": dynamic["requests_per_s"],
        "clients": N_CLIENTS,
        "requests": len(reqs),
        "ragged_rows": [1, MAX_ROWS],
        "sequential": sequential,
        "dynamic": dynamic,
        "mfu": perf.get("mfu"),
        "hbm_util": perf.get("hbm_util"),
        "arith_intensity": perf.get("arith_intensity"),
        "perf": perf,
        "throughput_ratio": round(
            dynamic["requests_per_s"]
            / max(sequential["requests_per_s"], 1e-9), 2),
        "note": ("closed-loop clients on CPU; sequential pays one "
                 "dispatch (and one compile per distinct ragged shape), "
                 "dynamic micro-batching coalesces concurrent requests "
                 "into bucket-padded batches"),
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
