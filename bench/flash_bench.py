#!/usr/bin/env python
"""Flash-attention kernel microbench (run on the real TPU).

Compares the Pallas blockwise kernel against the materializing jnp
reference at growing sequence lengths; prints one JSON line per config.
Numbers recorded in bench/PROFILE.md.

Since flash became the standard-path default (``use_flash=None`` auto-
enables at seq >= 1024), each row also records the promotion contract:
``auto_default`` confirms the default routing picks the kernel at that
sequence length, and ``meets_floor`` asserts the measured speedup holds
the 1.29x the promotion was justified by (bench/PROFILE.md, round 4) —
a row with ``meets_floor: false`` is a regression of the default path,
not just a slower kernel.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ops.attention import _auto_flash, FLASH_AUTO_SEQ_LEN
from deeplearning4j_tpu.ops.pallas import flash_attention
from deeplearning4j_tpu.parallel.unified import reference_attention


STEPS = 20
SPEEDUP_FLOOR = 1.29   # the measured win the default promotion rests on


def _chained(attn_fn):
    """20 data-dependent attention calls inside ONE jit — a single
    host↔device round trip, so remote-tunnel latency can't pollute the
    per-call time."""
    @jax.jit
    def run(q, k, v):
        def body(_, acc):
            out = attn_fn(acc, k, v)
            return acc + 1e-6 * out          # data dependency between steps
        return jax.lax.fori_loop(0, STEPS, body, q)
    return run


def bench(fn, args):
    float(jnp.sum(fn(*args).astype(jnp.float32)))        # warm + compile
    t0 = time.perf_counter()
    float(jnp.sum(fn(*args).astype(jnp.float32)))        # hard sync
    return (time.perf_counter() - t0) / STEPS * 1000


def main():
    rng = np.random.default_rng(0)
    h, d = 8, 64
    for t in (4096, 8192, 16384, 32768):
        q = jnp.asarray(rng.normal(size=(2, t, h * d)).astype(np.float32)
                        ).astype(jnp.bfloat16)
        k = jnp.asarray(rng.normal(size=(2, t, h * d)).astype(np.float32)
                        ).astype(jnp.bfloat16)
        v = jnp.asarray(rng.normal(size=(2, t, h * d)).astype(np.float32)
                        ).astype(jnp.bfloat16)
        f = _chained(lambda a, b, c: flash_attention(
            a, b, c, n_heads=h, causal=True))   # flash_block=0 default path
        flash_ms = bench(f, (q, k, v))
        try:
            r = _chained(lambda a, b, c: reference_attention(
                a, b, c, n_heads=h, causal=True))
            ref_ms = bench(r, (q, k, v))
        except Exception:        # [T,T] materialization OOMs at long seq
            ref_ms = None
        speedup = None if ref_ms is None else round(ref_ms / flash_ms, 2)
        print(json.dumps({
            "metric": "flash_attention_ms", "seq_len": t, "value": round(flash_ms, 2),
            "unit": "ms", "reference_ms": None if ref_ms is None else round(ref_ms, 2),
            "speedup": speedup,
            # the promoted-default contract: this seq routes to flash by
            # default, and the speedup that justified the promotion holds
            "auto_default": bool(_auto_flash(q, k)) and t >= FLASH_AUTO_SEQ_LEN,
            "speedup_floor": SPEEDUP_FLOOR,
            "meets_floor": None if speedup is None else speedup >= SPEEDUP_FLOOR}))


if __name__ == "__main__":
    main()
