"""Multichip scaling bench — federated telemetry measures the gang.

ROADMAP item 2's explicit deliverable: a multichip bench record that
COMPLETES under timeout and reports per-chip scaling efficiency.  Five
MULTICHIP rounds of the real-pod form died rc=124; this row is the
CPU-runnable form (the same `spawn_local_cluster` gang the tests use —
real multi-process jax.distributed over loopback), so it lands even
with the TPU tunnel down, and its numbers come from the telemetry
federation rather than per-process stopwatches:

- a coordinator ``UIServer`` runs in THIS process; every gang member's
  ``RemoteStatsRouter`` (injected via ``spawn_local_cluster``'s
  ``remote_ui``) stamps its steps onto it;
- per-worker throughput = 1 / median federated step time;
- ``per_chip_scaling_efficiency`` = (aggregate N-worker throughput / N)
  / single-worker throughput measured the same way;
- ``straggler_skew`` = max worker median step time / cluster median of
  medians (1.0 = perfectly even gang).

Since the self-healing-gangs PR the record also carries a **recovery**
section: a 2-worker gang runs under the
:class:`~deeplearning4j_tpu.resilience.supervisor.ClusterSupervisor`
with a fault-injected SIGKILL of one worker mid-fit; the supervisor
tears down, respawns from the latest verified checkpoint, and the
record reports the measured ``mttr_s`` (failure detection → first
post-restart federated step), ``steps_replayed`` and
``recovered: true`` — recovery time as a first-class efficiency number.

Since the elastic-device-pool PR the record also carries an **elastic**
section (own subprocess, like the mesh sweep): a grow scenario — one
continuous fit grows dp2→dp4 at an epoch boundary and its post-boundary
losses are diffed against a fixed-dp4 run (the checkpoint-consistency
number) — and a borrow/return scenario — a
:class:`~deeplearning4j_tpu.resilience.arbiter.DevicePoolArbiter` moves
2 chips from a live dp4 trainer to a live serve router and back under
threaded client load, reporting whether serve p99 held, the measured
gang grow-back MTTR, and that zero responses were dropped or garbled.

Prints ONE json line.  Env knobs: ``DL4J_TPU_MULTICHIP_WORKERS`` (4),
``DL4J_TPU_MULTICHIP_STEPS`` (16), ``DL4J_TPU_MULTICHIP_PORT`` (24211),
``DL4J_TPU_MULTICHIP_RECOVERY_STEPS`` (8).
"""

import functools
import json
import os
import sys

# the gang children unpickle the worker fn by module path: make this
# file importable as `multichip` in the children too (the established
# tests/cluster_workers.py pattern)
_HERE = os.path.dirname(os.path.abspath(__file__))
if _HERE not in sys.path:
    sys.path.insert(0, _HERE)
_REPO = os.path.dirname(_HERE)
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def train_worker(pid, n, steps=16):
    """One gang member: train a small MLP for ``steps`` steps; every
    step stamps onto the coordinator via the env-injected router (the
    launcher bootstraps it — no telemetry code here)."""
    import numpy as np
    import jax
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.train import Sgd
    from deeplearning4j_tpu.train.trainer import Trainer

    conf = (NeuralNetConfiguration.builder().seed(7 + pid)
            .updater(Sgd(0.05)).list()
            .layer(DenseLayer(n_out=64, activation="tanh"))
            .layer(OutputLayer(n_out=5, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(16)).build())
    net = MultiLayerNetwork(conf).init()
    trainer = Trainer(net)
    rng = np.random.default_rng(pid)
    x = rng.normal(size=(64, 16)).astype(np.float32)
    y = np.eye(5, dtype=np.float32)[rng.integers(0, 5, 64)]
    batch = DataSet(x, y)
    key = jax.random.key(pid)
    for _ in range(steps):
        key, sub = jax.random.split(key)
        trainer.step_batch(batch, sub)
    return {"pid": pid, "steps": steps}


def recovery_worker(pid, n, steps=8, workdir=None, kill_at=None):
    """Supervised gang member for the recovery record: fit over a
    ResumableIterator with per-iteration-pair checkpoints; in generation
    0 the LAST worker SIGKILLs itself mid-fit (faults ``kill`` action —
    real, uncatchable process death).  Respawned generations resume from
    their own verified checkpoints via the supervisor-injected
    ``DL4J_TPU_RESUME_FROM``."""
    import os
    import numpy as np
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.iterators import (ListDataSetIterator,
                                                   ResumableIterator)
    from deeplearning4j_tpu.io.checkpoint import CheckpointListener
    from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.resilience import faults, supervisor
    from deeplearning4j_tpu.train import Sgd
    from deeplearning4j_tpu.train.trainer import Trainer

    generation = int(os.environ.get(supervisor.GENERATION_ENV, "0"))
    if kill_at is None:
        kill_at = max(2, steps - 2)
    if generation == 0 and pid == n - 1:
        # the chaos: REAL SIGKILL before step kill_at commits — only in
        # the first generation (the supervisor also strips the env fault
        # plan on respawn; this programmatic plan is gated here)
        faults.install_fault_plan(
            faults.FaultPlan.parse(f"trainer.step@{kill_at}:kill"))

    conf = (NeuralNetConfiguration.builder().seed(19 + pid)
            .updater(Sgd(0.05)).list()
            .layer(DenseLayer(n_out=32, activation="tanh"))
            .layer(OutputLayer(n_out=5, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(16)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(37 + pid)
    x = rng.normal(size=(steps * 16, 16)).astype(np.float32)
    y = np.eye(5, dtype=np.float32)[rng.integers(0, 5, steps * 16)]
    batches = [DataSet(x[i:i + 16], y[i:i + 16])
               for i in range(0, steps * 16, 16)]
    iterator = ResumableIterator(ListDataSetIterator(batches))
    ckpt_dir = os.path.join(workdir, f"w{pid}")
    ckpt = CheckpointListener(ckpt_dir, save_every_n_iterations=2,
                              keep_last=3, iterator=iterator)
    resume = os.environ.get(supervisor.RESUME_ENV)
    trainer = Trainer(net, listeners=[ckpt])
    trainer.fit(iterator, epochs=1,
                resume_from=(ckpt_dir if resume else None))
    return {"pid": pid, "generation": generation,
            "iteration": net.iteration}


def _run_recovery(server, steps, port, workdir):
    """The recovery row: a supervised 2-worker gang with an injected
    SIGKILL; returns measured MTTR + steps replayed."""
    from deeplearning4j_tpu.obs.remote import ClusterStore
    from deeplearning4j_tpu.resilience.supervisor import ClusterSupervisor
    server.cluster = ClusterStore()
    import multichip as _self
    fn = functools.partial(_self.recovery_worker, steps=steps,
                           workdir=workdir)
    sup = ClusterSupervisor(
        fn, n_processes=2, checkpoint_dir=workdir, max_restarts=2,
        port=port, timeout=300.0, remote_ui=server.url,
        cluster_store=server.cluster,
        extra_env={"PYTHONPATH": _HERE + os.pathsep
                   + os.environ.get("PYTHONPATH", "")})
    run = sup.run()
    incident = run.incidents[0] if run.incidents else None
    return {
        "recovered": bool(run.incidents) and len(run.results) == 2,
        "restarts": len(run.incidents),
        "generations": run.generations,
        "mttr_s": (None if incident is None or incident.mttr_s is None
                   else round(incident.mttr_s, 3)),
        "steps_replayed": (None if incident is None
                           else incident.steps_replayed),
        "reason": None if incident is None else incident.reason,
        "note": ("2-worker supervised gang; one worker SIGKILLed "
                 "mid-fit by the fault harness, gang respawned from "
                 "the latest verified checkpoint; mttr_s = detection "
                 "to first post-restart federated step"),
    }


def mesh_sweep_main():
    """ISSUE-14 deliverable: the SAME model stepped under several
    composable layouts on one host's 8-device virtual CPU mesh —
    measured steps/s per layout, the analytic per-step collective-bytes
    estimate from ``MeshLayout.collective_bytes_per_step``, and
    per-layout arithmetic intensity pulled from the compiled program's
    XLA cost_analysis (the PR-6 cost model; collectives show up as
    bytes, so layout choices move the measured intensity).  Runs
    in-process — ``main()`` launches it as a subprocess with the forced
    device count so the gang runs above keep their 1-device children.
    Prints ONE json line."""
    import time

    import numpy as np
    import jax

    from deeplearning4j_tpu.config import set_config
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.obs import costmodel
    from deeplearning4j_tpu.train import Sgd
    from deeplearning4j_tpu.train.trainer import Trainer

    layouts = [s for s in os.environ.get(
        "DL4J_TPU_MESH_SWEEP_LAYOUTS",
        "dp4,tp4,dp2xtp2,dp2xpp2").split(",") if s]
    steps = int(os.environ.get("DL4J_TPU_MESH_SWEEP_STEPS", "10"))
    width, hidden, classes, batch = 64, 256, 8, 64
    set_config(device_feed=False)   # direct fit_batch loop, no feeder thread

    def build_net():
        conf = (NeuralNetConfiguration.builder().seed(31)
                .updater(Sgd(0.05)).list()
                .layer(DenseLayer(n_out=hidden, activation="relu"))
                .layer(DenseLayer(n_out=hidden, activation="tanh"))
                .layer(OutputLayer(n_out=classes, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(width)).build())
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        return MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(5)
    x = rng.normal(size=(batch, width)).astype(np.float32)
    y = np.eye(classes, dtype=np.float32)[rng.integers(0, classes, batch)]
    batch_ds = DataSet(x, y)

    def run(layout):
        net = build_net()
        mb = 2 if layout and "pp" in layout else 1
        trainer = Trainer(net, layout=layout, n_microbatches=mb)
        key = jax.random.key(11)
        for _ in range(2):      # compile + settle
            key, sub = jax.random.split(key)
            jax.block_until_ready(trainer.fit_batch(batch_ds, sub))
        t0 = time.perf_counter()
        for _ in range(steps):
            key, sub = jax.random.split(key)
            loss = trainer.fit_batch(batch_ds, sub)
        jax.block_until_ready(loss)
        dt = (time.perf_counter() - t0) / steps
        row = {"steps_per_s": round(1.0 / dt, 3),
               "step_ms": round(dt * 1e3, 3)}
        stamp = None
        if trainer._bake_args is not None:
            stamp = costmodel.measure(trainer._step, trainer._bake_args,
                                      dt, kind=f"train:{layout or 'single'}")
        if stamp:
            row.update({k: stamp[k] for k in
                        ("arith_intensity", "flops_per_step",
                         "bytes_per_step", "roofline_bound")
                        if k in stamp})
        if trainer._layout is not None:
            param_bytes = sum(
                int(l.size) * l.dtype.itemsize
                for l in jax.tree_util.tree_leaves(net.params_)
                if hasattr(l, "size"))
            act_bytes = batch * hidden * 4
            row["collective_bytes_per_step"] = \
                trainer._layout.collective_bytes_per_step(param_bytes,
                                                          act_bytes)
            row["collective_bytes_source"] = "analytic_estimate"
            row["layout"] = trainer._layout.describe()
        return row

    baseline = run(None)
    rows = {}
    for layout in layouts:
        try:
            rows[layout] = run(layout)
        except Exception as e:   # a layout that cannot build on this host
            rows[layout] = {"error": f"{type(e).__name__}: {str(e)[:160]}"}
    print(json.dumps({
        "metric": "mesh_layout_sweep",
        "value": max((r.get("steps_per_s") or 0.0) for r in rows.values()),
        "unit": "steps_per_s",
        "model": f"mlp_{width}x{hidden}x{hidden}x{classes}",
        "batch": batch,
        "steps_timed": steps,
        "single_device": baseline,
        "layouts": rows,
        "note": ("same model, same batches, one unified mesh — layouts "
                 "selected via Trainer(layout=...); steps/s measured "
                 "after compile, arith intensity from XLA cost_analysis "
                 "of each layout's compiled step, collective bytes from "
                 "the MeshLayout analytic model (virtual CPU devices: "
                 "relative layout cost, not TPU wall time)"),
    }))
    return 0


def elastic_main():
    """The elastic-device-pool record (ISSUE 19).  Two scenarios on the
    forced 8-device virtual CPU mesh, in-process:

    - **grow**: the SAME model/data/seed run twice — fixed dp4, and
      dp2 growing to dp4 at an epoch boundary inside one continuous fit
      (dropout active, width-invariant partitionable RNG).  Reports the
      max post-boundary per-step loss delta: the checkpoint-consistent
      reshard makes it ~0.
    - **arbiter**: a DevicePoolArbiter borrows 2 chips from a live dp4
      trainer for a live serve router under threaded client load, then
      returns them; reports serve p99 steady vs during the flips, the
      gang grow-back MTTR, and zero dropped/garbled responses.

    Prints ONE json line."""
    import tempfile
    import threading
    import time

    import numpy as np

    from deeplearning4j_tpu.data.iterators import ArrayDataSetIterator
    from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.resilience.arbiter import (DevicePoolArbiter,
                                                       TrainerGang)
    from deeplearning4j_tpu.serve import ModelRegistry, ReplicaRouter
    from deeplearning4j_tpu.train import Sgd
    from deeplearning4j_tpu.train.trainer import Trainer

    def mlp(seed=11, dropout=0.8):
        conf = (NeuralNetConfiguration.builder().seed(seed)
                .updater(Sgd(0.1)).weight_init("xavier").list()
                .layer(DenseLayer(n_out=16, activation="relu",
                                  dropout=dropout))
                .layer(DenseLayer(n_out=16, activation="tanh",
                                  dropout=dropout))
                .layer(OutputLayer(n_out=4, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(8)).build())
        return MultiLayerNetwork(conf)

    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 8)).astype(np.float32)
    w = rng.normal(size=(8, 4)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[np.argmax(x @ w, -1)]
    epochs, boundary = 4, 2

    def run(start, resize_to=None):
        net = mlp()
        trainer = Trainer(net, layout=start)
        losses = []

        class Rec:
            def iteration_done(self, net, it, ep, loss):
                losses.append(float(loss))

            def on_epoch_end(self, net, epoch, info):
                if resize_to is not None and epoch + 1 == boundary:
                    trainer.request_resize(resize_to)

        trainer.bus.listeners.append(Rec())
        trainer.fit(ArrayDataSetIterator(x, y, 16, shuffle=False),
                    epochs=epochs)
        return losses, trainer

    fixed_losses, _ = run("dp4")
    elastic_losses, trainer = run("dp2", resize_to=4)
    cut = boundary * (len(fixed_losses) // epochs)
    delta = max(abs(a - b) for a, b in
                zip(elastic_losses[cut:], fixed_losses[cut:]))
    grow = {
        "from_width": 2, "to_width": 4, "resize_epoch": boundary,
        "post_boundary_max_loss_delta": float(f"{delta:.3e}"),
        "matches_fixed_width": bool(delta <= 1e-6),
        "final_layout": trainer._layout.describe(),
        "note": ("one continuous fit grows dp2->dp4 at the epoch "
                 "boundary; post-boundary per-step losses diffed "
                 "against a fixed-dp4 run (dropout active)"),
    }

    # ----- borrow/return under live serve load
    workdir = tempfile.mkdtemp(prefix="dl4j_tpu_elastic_")
    snet = mlp(seed=23, dropout=None).init()
    path = os.path.join(workdir, "serve.zip")
    snet.save(path)
    models = ModelRegistry(max_batch=8, max_latency_ms=2, queue_limit=64)
    models.deploy("m", path)
    router = ReplicaRouter(models, "m", replicas=2, max_replicas=4)
    trainer = Trainer(mlp(), layout="dp4")
    it = ArrayDataSetIterator(x, y, 16, shuffle=False)
    trainer.fit(it, epochs=1)
    arb = DevicePoolArbiter(router, TrainerGang(trainer), min_train=2,
                            chips_per_flip=2, cooldown_s=0.0, serve_chips=2)
    xs = x[:8]
    expected = np.asarray(snet.output(xs))
    stop, errors, lat = threading.Event(), [], []

    def client():
        while not stop.is_set():
            t = time.perf_counter()
            try:
                out, _ = models.predict_versioned("m", xs, timeout_s=30)
            except Exception as e:
                errors.append(repr(e)[:200])
                return
            lat.append(time.perf_counter() - t)
            if not np.allclose(out, expected, rtol=1e-5, atol=1e-6):
                errors.append("garbled response")
                return

    def p99(samples):
        s = sorted(samples) or [0.0]
        return s[int(0.99 * (len(s) - 1))]

    for _ in range(3):                   # compile + settle the engine
        models.predict_versioned("m", xs, timeout_s=30)
    threads = [threading.Thread(target=client) for _ in range(3)]
    for th in threads:
        th.start()
    deadline = time.monotonic() + 5.0    # steady-state sample
    while len(lat) < 30 and time.monotonic() < deadline:
        time.sleep(0.02)
    n0, p99_steady = len(lat), p99(lat)
    borrowed = arb.borrow()
    trainer.fit(it, epochs=1)            # shrink lands at the boundary
    width_during = trainer._layout.spec.total()
    t_return = time.perf_counter()
    returned = arb.return_chips()
    trainer.fit(it, epochs=1)            # ... grow-back too
    mttr_s = time.perf_counter() - t_return
    stop.set()
    for th in threads:
        th.join(timeout=30)
    p99_flips = p99(lat[n0:])
    arbiter = {
        "borrowed": bool(borrowed), "returned": bool(returned),
        "width_during_borrow": width_during,
        "width_restored": trainer._layout.spec.total() == 4,
        "pool": arb.snapshot(),
        "served": len(lat),
        "zero_dropped_or_garbled": not errors,
        "errors": errors[:3],
        "serve_p99_ms_steady": round(p99_steady * 1e3, 3),
        "serve_p99_ms_during_flips": round(p99_flips * 1e3, 3),
        "p99_held": bool(not errors
                         and p99_flips <= max(p99_steady * 5, 0.25)),
        "grow_back_mttr_s": round(mttr_s, 3),
        "note": ("2 chips borrowed from a live dp4 trainer for the "
                 "serve router and returned under 3 threaded clients; "
                 "mttr_s = return_chips() to the gang trained back at "
                 "dp4 (includes the boundary epoch + reshard)"),
    }
    ok = (grow["matches_fixed_width"] and arbiter["width_restored"]
          and arbiter["zero_dropped_or_garbled"])
    print(json.dumps({
        "metric": "elastic_pool", "value": 1.0 if ok else 0.0,
        "unit": "ok", "grow": grow, "arbiter": arbiter,
    }))
    return 0


def _run_elastic(timeout_s=420.0):
    """Run the elastic record in a subprocess with the forced 8-device
    virtual CPU topology and the width-invariant partitionable RNG (the
    1e-6 grow contract depends on it)."""
    import subprocess
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags = (flags + " --xla_force_host_platform_device_count=8").strip()
    env = dict(os.environ, JAX_PLATFORMS="cpu", XLA_FLAGS=flags,
               JAX_THREEFRY_PARTITIONABLE="1")
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--elastic"],
        capture_output=True, text=True, timeout=timeout_s, env=env)
    lines = [ln for ln in proc.stdout.splitlines() if ln.startswith("{")]
    if lines:
        return json.loads(lines[-1])
    return {"error": (proc.stderr or "no output")[-300:]}


def _run_mesh_sweep(timeout_s=420.0):
    """Run the sweep in a subprocess with the forced 8-device virtual
    CPU topology (the parent keeps its own device view for the gangs)."""
    import subprocess
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags = (flags + " --xla_force_host_platform_device_count=8").strip()
    env = dict(os.environ, JAX_PLATFORMS="cpu", XLA_FLAGS=flags)
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--mesh-sweep"],
        capture_output=True, text=True, timeout=timeout_s, env=env)
    lines = [ln for ln in proc.stdout.splitlines() if ln.startswith("{")]
    if lines:
        return json.loads(lines[-1])
    return {"error": (proc.stderr or "no output")[-300:]}


def _fetch_json(url):
    import urllib.request
    with urllib.request.urlopen(url, timeout=5) as resp:
        return json.loads(resp.read())


def _run_gang(server, n_workers, steps, port):
    """One federated gang run; returns the coordinator's summary of it.
    A fresh ClusterStore per run keeps the baseline's telemetry out of
    the N-worker medians."""
    from deeplearning4j_tpu.obs.remote import ClusterStore
    from deeplearning4j_tpu.parallel.launcher import spawn_local_cluster
    server.cluster = ClusterStore()
    # reference the worker through the IMPORTED module, not __main__:
    # the gang children unpickle `multichip.train_worker` via the
    # PYTHONPATH handed to them below
    import multichip as _self
    fn = functools.partial(_self.train_worker, steps=steps)
    spawn_local_cluster(fn, n_processes=n_workers, port=port,
                        timeout=420.0, remote_ui=server.url,
                        extra_env={"PYTHONPATH": _HERE + os.pathsep
                                   + os.environ.get("PYTHONPATH", "")})
    return _fetch_json(server.url + "cluster.json")


def _throughputs(summary):
    """worker → steps/s from the federated median step time (None when a
    worker never reported a measurable median)."""
    out = {}
    for name, w in summary.get("workers", {}).items():
        med = w.get("median_step_ms")
        out[name] = (1e3 / med) if med else None
    return out


def main():
    import tempfile
    n_workers = int(os.environ.get("DL4J_TPU_MULTICHIP_WORKERS", "4"))
    steps = int(os.environ.get("DL4J_TPU_MULTICHIP_STEPS", "16"))
    port = int(os.environ.get("DL4J_TPU_MULTICHIP_PORT", "24211"))
    recovery_steps = int(os.environ.get("DL4J_TPU_MULTICHIP_RECOVERY_STEPS",
                                        "8"))
    from deeplearning4j_tpu.obs.ui_server import UIServer
    server = UIServer(port=0)
    try:
        # single-worker baseline under the IDENTICAL harness (same spawn,
        # same distributed runtime, same telemetry path)
        base_summary = _run_gang(server, 1, steps, port)
        base_tp = [t for t in _throughputs(base_summary).values() if t]
        if not base_tp:
            raise RuntimeError(f"baseline run produced no federated step "
                               f"timings: {base_summary}")
        baseline = base_tp[0]

        gang_summary = _run_gang(server, n_workers, steps, port + 173)
        tps = _throughputs(gang_summary)
        measured = [t for t in tps.values() if t]
        if len(measured) < n_workers:
            raise RuntimeError(f"only {len(measured)}/{n_workers} workers "
                               f"reported step timings: {gang_summary}")
        aggregate = sum(measured)
        efficiency = (aggregate / n_workers) / baseline
        skew = gang_summary.get("straggler_skew") or 1.0

        # the self-healing row: kill-and-heal under the supervisor,
        # measured from the same federated telemetry
        recovery = _run_recovery(server, recovery_steps, port + 391,
                                 tempfile.mkdtemp(prefix="dl4j_tpu_rec_"))
        # the unified-mesh layout sweep (own subprocess: needs the
        # forced 8-device topology the gang children must not inherit)
        try:
            mesh_sweep = _run_mesh_sweep()
        except Exception as e:
            mesh_sweep = {"error": str(e)[:200]}
        # the elastic-pool row (own subprocess: needs the forced
        # 8-device topology AND the partitionable RNG)
        try:
            elastic = _run_elastic()
        except Exception as e:
            elastic = {"error": str(e)[:200]}
        print(json.dumps({
            "metric": "multichip_scaling_efficiency",
            "value": round(efficiency, 4),
            "unit": "fraction",
            "n_workers": n_workers,
            "steps_per_worker": steps,
            "per_chip_scaling_efficiency": round(efficiency, 4),
            "straggler_skew": round(skew, 4),
            "recovery": recovery,
            "mesh_sweep": mesh_sweep,
            "elastic": elastic,
            "detail": {
                "baseline_steps_per_s": round(baseline, 3),
                "aggregate_steps_per_s": round(aggregate, 3),
                "workers": gang_summary.get("workers", {}),
                "source": "federated_telemetry",
                "note": ("CPU loopback gang (all workers share the host's "
                         "cores, so efficiency < 1 is expected and real); "
                         "throughput = 1/median federated step time per "
                         "worker, scraped from the coordinator's "
                         "/cluster.json"),
            },
        }))
        return 0
    finally:
        server.stop()


if __name__ == "__main__":
    if "--mesh-sweep" in sys.argv:
        sys.exit(mesh_sweep_main())
    if "--elastic" in sys.argv:
        sys.exit(elastic_main())
    sys.exit(main())
