#!/usr/bin/env python
"""DP scaling measurement on the 8-device virtual CPU mesh (VERDICT r2
weak #3: round 2 ASSERTED near-linear DP scaling; this measures it).

Weak scaling: fixed per-device batch, dp = 1/2/4/8 over the virtual
mesh, real ``ParallelWrapper`` trainer (psum gradient allreduce inside
the donated jit step).  CPU collectives model the dp *overhead
structure* (program + collective per step, same XLA SPMD partitioner
the TPU path uses), not ICI bandwidth — the TPU communication estimate
comes from the gradient-bytes/ICI-rate model in bench.py, recorded next
to these measurements.

Prints ONE json line; run standalone or via bench.py (subprocess).
"""

import json
import os
import sys
import time

# must precede jax import; sitecustomize pins the axon TPU platform,
# so the config.update below is ALSO required
os.environ["JAX_PLATFORMS"] = "cpu"
# force EXACTLY 8 virtual devices (a pre-existing count in XLA_FLAGS
# would silently shrink the dp sweep)
_flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
          if "xla_force_host_platform_device_count" not in f]
os.environ["XLA_FLAGS"] = " ".join(
    _flags + ["--xla_force_host_platform_device_count=8"])

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def measure(per_device_batch: int = 32, steps: int = 8,
            warmup: int = 2) -> dict:
    import jax.numpy as jnp
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.models import lenet
    from deeplearning4j_tpu.parallel.data_parallel import ParallelWrapper
    from deeplearning4j_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(0)
    rows = []
    for dp in (1, 2, 4, 8):
        net = lenet(height=32, width=32, channels=3)
        mesh = make_mesh(data=dp, devices=jax.devices()[:dp])
        trainer = ParallelWrapper(net, mesh=mesh)
        batch = per_device_batch * dp
        ds = DataSet(
            jnp.asarray(rng.normal(size=(batch, 32, 32, 3))
                        .astype(np.float32)),
            jnp.asarray(np.eye(10, dtype=np.float32)[
                rng.integers(0, 10, batch)]))
        key = jax.random.key(0)
        for _ in range(warmup):
            loss = trainer.fit_batch(ds, key)
        float(loss)
        # best-of-3: host-load noise on the shared virtual devices was
        # ±2x run to run (BENCH_r03 vs r04 spreads); min is the stable
        # estimator of the program's actual cost
        dt = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(steps):
                loss = trainer.fit_batch(ds, key)
            float(loss)
            dt = min(dt, (time.perf_counter() - t0) / steps)
        rows.append({"dp": dp, "global_batch": batch,
                     "step_ms": round(dt * 1000, 2),
                     "img_per_sec": round(batch / dt, 1)})
    t1 = rows[0]["step_ms"]
    for r in rows:
        # virtual CPU devices SHARE the host cores, so total work scales
        # with dp and step time grows ~linearly; the measurable quantity
        # is the SPMD overhead factor — partitioned program + psum
        # allreduce vs dp x the single-device work.  1.0 = the
        # partitioner/collective added nothing; >1 = overhead.
        r["spmd_overhead_factor"] = round(r["step_ms"] / (t1 * r["dp"]), 3)
    return {"metric": "dp_weak_scaling_cpu_mesh",
            "per_device_batch": per_device_batch,
            "model": "lenet_cifar10_shape", "rows": rows,
            "note": ("virtual devices share host cores: spmd_overhead_"
                     "factor isolates partitioner+collective cost; ICI "
                     "bandwidth modeled separately (bench.py "
                     "bench_dp_scaling → ici_model_v5e8)")}


if __name__ == "__main__":
    print(json.dumps(measure()))
    sys.exit(0)
