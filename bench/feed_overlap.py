#!/usr/bin/env python
"""CPU micro-bench: DeviceFeeder on vs off over an ETL-heavy ragged epoch.

Measures the device-feed pipeline's two effects without a TPU:

* **overlap** — per-batch host ETL (normalize + noise passes) runs on
  the feeder's background stage under device execution instead of
  serializing with it → steps/sec.  The loop carries a per-step score
  listener (the common ScoreIterationListener configuration), which
  syncs each step's loss — exactly the regime where inline ETL
  serializes host against device and the feeder's background stage
  wins it back;
* **recompile guard** — the 1031-example / batch-64 epoch has a ragged
  tail; with the feeder's shape bucketing the train step compiles ONCE
  (jit cache size 1), without it the tail shape compiles a second
  program.

Run standalone (``python bench/feed_overlap.py``) or via the
``feed_overlap`` record in ``bench.py`` (subprocess pinned to
``JAX_PLATFORMS=cpu`` — the record stays measurable when the TPU tunnel
is down).  Prints ONE json line.
"""

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

N_EXAMPLES = 1031     # deliberately non-divisible: full batches + ragged tail
N_FEATURES = 512
BATCH = 64
EPOCHS = 3
ETL_NOISE_PASSES = 6  # host work per batch the feeder can hide


def _etl_iterator(x, y):
    """Generator iterator with deliberate per-batch host ETL (the work
    the feeder's background stage overlaps with the device step)."""
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.iterators import GeneratorDataSetIterator

    def factory():
        n = x.shape[0]
        for lo in range(0, n, BATCH):
            xb = x[lo:lo + BATCH]
            xb = (xb - xb.mean(axis=0)) / (xb.std(axis=0) + 1e-6)
            rng = np.random.default_rng(lo)
            for _ in range(ETL_NOISE_PASSES):
                xb = xb + rng.normal(scale=1e-3, size=xb.shape)
            yield DataSet(xb.astype(np.float32), y[lo:lo + BATCH])

    return GeneratorDataSetIterator(factory)


class _ScoreSync:
    """Per-step host read of the loss (ScoreIterationListener regime) —
    the sync that makes inline ETL serialize against the device."""

    def iteration_done(self, model, iteration, epoch, score):
        self.last = float(score)


def run_mode(device_feed: bool) -> dict:
    from deeplearning4j_tpu.config import set_config
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.input_type import InputType
    from deeplearning4j_tpu.nn.layers.core import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.train.step_cache import jit_cache_entries
    from deeplearning4j_tpu.train.trainer import Trainer
    from deeplearning4j_tpu.train.updaters import Sgd

    set_config(device_feed=device_feed, shape_bucketing=device_feed)
    rng = np.random.default_rng(7)
    x = rng.normal(size=(N_EXAMPLES, N_FEATURES)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, N_EXAMPLES)]
    # distinct seed per mode → distinct step-cache key, so the OFF run's
    # compiled programs cannot leak into the ON run's recompile count
    conf = (NeuralNetConfiguration.builder()
            .seed(1000 + int(device_feed)).updater(Sgd(0.05)).list()
            .layer(DenseLayer(n_out=1024, activation="relu"))
            .layer(DenseLayer(n_out=1024, activation="relu"))
            .layer(OutputLayer(n_out=10, activation="softmax"))
            .set_input_type(InputType.feed_forward(N_FEATURES)).build())
    net = MultiLayerNetwork(conf).init()
    trainer = Trainer(net, listeners=[_ScoreSync()])
    iterator = _etl_iterator(x, y)

    trainer.fit(iterator, epochs=1)       # compile + warm both shapes
    float(net._score)                     # sync fence
    # the warm epoch queued this step's background cost analysis (a
    # duplicate XLA compile) — and the OFF run's may still be in flight
    # when the ON run measures; drain so it never contends with the
    # region that decides the off-vs-on speedup
    from deeplearning4j_tpu.obs import costmodel
    costmodel.drain()
    t0 = time.perf_counter()
    trainer.fit(iterator, epochs=EPOCHS)
    float(net._score)                     # sync fence inside the region
    dt = time.perf_counter() - t0
    n_steps = -(-N_EXAMPLES // BATCH) * EPOCHS
    return {
        "steps_per_sec": round(n_steps / dt, 2),
        "recompiles": jit_cache_entries(trainer._step),
    }


def main() -> int:
    off = run_mode(False)
    on = run_mode(True)
    # roofline stamp: the trainers above ran under the cost model, so
    # the record carries MFU / HBM utilization / arithmetic intensity
    # from the compiled step's own cost_analysis — measurable on CPU,
    # so a tunnel-down bench round still reports them
    from deeplearning4j_tpu.obs import costmodel
    costmodel.drain()   # flush any still-queued background analysis
    perf = costmodel.bench_detail() or {}
    result = {
        "metric": "feed_overlap",
        "batch": BATCH, "examples": N_EXAMPLES, "epochs": EPOCHS,
        "prefetch_off_steps_per_sec": off["steps_per_sec"],
        "prefetch_on_steps_per_sec": on["steps_per_sec"],
        "speedup": round(on["steps_per_sec"] / max(off["steps_per_sec"],
                                                   1e-9), 3),
        "recompiles": {"off": off["recompiles"], "on": on["recompiles"]},
        "mfu": perf.get("mfu"),
        "hbm_util": perf.get("hbm_util"),
        "arith_intensity": perf.get("arith_intensity"),
        "perf": perf,
        "note": ("per-step score sync (ScoreIterationListener regime); "
                 "etl waits land in tpudl_data_etl_wait_seconds"),
    }
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
