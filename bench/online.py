#!/usr/bin/env python
"""CPU micro-bench: the closed continual-learning loop, timed end to end.

Measures the ``tpudl.online`` subsystem's three operational numbers
without a TPU (docs/online.md):

* **feedback→deploy latency** — wall time from the first feedback
  record landing in the spool to a gated hot-swap completing: spool
  drain + round trigger + fine-tune from the latest verified checkpoint
  + gate eval + registry verified hot-swap.  This is the loop's
  "fine-tune→serve turnaround" headline (the Gemma-on-TPU serving
  comparison's axis, PAPERS.md).
* **gate eval seconds** — verify + score candidate and incumbent on the
  held-out slice + decide (the pure gate overhead a deploy pays).
* **rollback MTTR** — regression detection to the rolled-back previous
  version serving again, measured by injecting a post-deploy serve
  error burst under a live :class:`DeployWatch`.

Run standalone (``python bench/online.py``) or via the ``online``
record in ``bench.py`` (subprocess pinned to ``JAX_PLATFORMS=cpu`` —
the record rides BOTH the normal and tunnel-down skip paths, like
``serving``/``multichip``).  Prints ONE json line.
"""

import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

N_IN, N_OUT = 16, 4
FEEDBACK_RECORDS = 96
BATCH = 16


def _teacher(rng):
    return rng.normal(size=(N_IN, N_OUT)).astype(np.float32)


def _make_xy(w, n, seed):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, N_IN)).astype(np.float32)
    y = np.eye(N_OUT, dtype=np.float32)[np.argmax(x @ w, -1)]
    return x, y


def _build_net(seed):
    from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.train import Adam
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=32, activation="relu"))
            .layer(OutputLayer(n_out=N_OUT, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(N_IN)).build())
    return MultiLayerNetwork(conf).init()


def main() -> dict:
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.iterators import ListDataSetIterator
    from deeplearning4j_tpu.obs.registry import get_registry
    from deeplearning4j_tpu.online import (DeployWatch, EvalGate,
                                           OnlineConfig, OnlineTrainer)
    from deeplearning4j_tpu.serve import FeedbackLog, ModelRegistry

    rng = np.random.default_rng(0)
    w = _teacher(rng)
    workdir = tempfile.mkdtemp(prefix="tpudl_bench_online_")

    # a briefly-trained base model, deployed as the incumbent
    net = _build_net(1)
    x0, y0 = _make_xy(w, 64, 1)
    net.fit(ListDataSetIterator(
        [DataSet(x0[i:i + BATCH], y0[i:i + BATCH])
         for i in range(0, 64, BATCH)]), epochs=1)
    base = os.path.join(workdir, "base.zip")
    net.save(base)
    registry = ModelRegistry(max_batch=8, max_latency_ms=2.0)
    registry.deploy("bench", base)

    hx, hy = _make_xy(w, 128, 3)
    gate = EvalGate(ListDataSetIterator([DataSet(hx, hy)]),
                    metric="accuracy", min_delta=1.0)   # non-regression only
    spool = os.path.join(workdir, "spool")
    log = FeedbackLog(spool)
    trainer = OnlineTrainer(
        registry, "bench", spool, os.path.join(workdir, "online"), gate,
        base, config=OnlineConfig(min_records=FEEDBACK_RECORDS,
                                  batch_size=BATCH,
                                  max_records_per_round=FEEDBACK_RECORDS))

    # ---- feedback → deploy: first record spooled to hot-swap complete
    xf, yf = _make_xy(w, FEEDBACK_RECORDS, 2)
    t0 = time.perf_counter()
    log.extend(xf, yf)
    log.flush()
    decision = trainer.run_once(force=True)
    feedback_to_deploy_s = time.perf_counter() - t0
    deployed = decision["status"] == "deployed"
    gate_eval_s = decision.get("gate", {}).get("gate_seconds", 0.0)

    # ---- rollback MTTR: a live watch over an injected serve error burst
    import threading
    reg = get_registry()
    requests = reg.labeled_counter("tpudl_serve_requests_total")
    watch = DeployWatch(registry, "bench", window_s=10.0, poll_s=0.02,
                        error_rate_max=0.25, min_requests=4)

    def _burst():
        # the burst lands AFTER the watch's baseline snapshot — the
        # delta is what detection reads
        time.sleep(0.05)
        requests.inc(16, status="error")
        requests.inc(4, status="ok")

    t1 = time.perf_counter()
    threading.Thread(target=_burst, daemon=True).start()
    verdict = watch.run()
    rollback_wall_s = time.perf_counter() - t1

    registry.close()
    log.close()
    spool_records = reg.counter("tpudl_online_spool_records_total").value
    return {
        "metric": "online_feedback_to_deploy_seconds",
        "value": round(feedback_to_deploy_s, 3),
        "unit": "seconds",
        "deployed": deployed,
        "gate_eval_s": round(gate_eval_s, 3),
        "fine_tune_s": round(decision.get("fine_tune_s", 0.0), 3),
        "rollback_mttr_s": round(verdict.get("mttr_s", 0.0), 4),
        "rollback_detect_to_restore_s": round(rollback_wall_s, 3),
        "rolled_back": bool(verdict.get("rolled_back")),
        "records": int(FEEDBACK_RECORDS),
        "spool_records_total": int(spool_records),
        "gate_decision": decision.get("gate", {}).get("reason"),
        "note": ("CPU form of the closed loop: spool→round→fine-tune→"
                 "gate→verified hot-swap, then an injected error burst "
                 "under DeployWatch; real-HW numbers scale with model "
                 "size, not loop overhead"),
    }


if __name__ == "__main__":
    print(json.dumps(main()))
    sys.exit(0)
